"""Benchmark: flagship GPT (BERT-base scale) training throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference publishes no numbers (BASELINE.md); the operative
target is BERT-base seq/sec/chip >= 0.8x a V100 CUDA chip.  NVIDIA's
public BERT-base fp16 seq-512 training figure on one V100 is ~107 seq/s,
so vs_baseline = value / (0.8 * 107).  On CPU fallback (no TPU tunnel)
the config is shrunk and vs_baseline is reported against the same target
for continuity (expect << 1 on CPU).
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

# BENCH_PROFILE=1: run with the host tracer live and embed an
# observability snapshot (jit-cache hit rate, step p50/p95) in the JSON.
# Off by default — tracing adds per-op host overhead to the eager paths.
PROFILE = os.environ.get("BENCH_PROFILE", "") not in ("", "0")
# BENCH_SERVE=1: also run the serving bench (InferenceEngine under
# concurrent clients) and embed req/s + p50/p99 latency in the JSON.
SERVE = os.environ.get("BENCH_SERVE", "") not in ("", "0")
# BENCH_INT8=1: serving leg comparing the int8 artifact path against
# fp32 — latency + top-1 agreement through the same InferenceEngine.
INT8 = os.environ.get("BENCH_INT8", "") not in ("", "0")
# BENCH_PS=1: sharded parameter-server leg — lookups/s, pull-latency
# p50/p99, device-cache hit rate, and recovery-after-host-loss seconds
# through the replicated failover path.
PS = os.environ.get("BENCH_PS", "") not in ("", "0")


def _metrics_snapshot():
    """Selected profiler metrics for the BENCH JSON."""
    from paddle_tpu.profiler import metrics as pm
    snap = pm.snapshot()
    hits = snap.get("dispatch.jit_cache.hit", 0)
    misses = snap.get("dispatch.jit_cache.miss", 0)
    out = {
        "dispatch_count": snap.get("dispatch.count", 0),
        "jit_cache": {
            "hits": hits, "misses": misses,
            "hit_rate": round(hits / (hits + misses), 4)
            if (hits + misses) else None,
        },
    }
    steps = snap.get("bench.step_latency_ms")
    if isinstance(steps, dict) and steps.get("count"):
        out["step_latency_ms"] = {k: round(steps[k], 3)
                                  for k in ("p50", "p95", "avg", "max")
                                  if steps.get(k) is not None}
    return out


def _compile_cache_info():
    """Persistent-XLA-cache accounting for the BENCH JSON: entry counts
    let a relaunch prove it skipped recompiles (new_entries == 0)."""
    from paddle_tpu.utils import compile_cache as cc
    d = cc.cache_dir()
    return {"dir": d, "entries": cc.entry_count(d)} if d else None


def _artifact_store_info():
    """AOT artifact-store accounting (hits == executables loaded
    instead of compiled; a warm relaunch reports misses == 0)."""
    from paddle_tpu.utils import artifact_store as aot
    if aot.active() is None:
        return None
    s = aot.stats()
    return {"dir": aot.active().root, "entries": len(aot.active()),
            "hits": s["hit"], "misses": s["miss"],
            "stores": s["store"], "corrupt": s["corrupt"]}


def _probe_backend(timeout_s: float = 240.0) -> bool:
    """True if the default (TPU/axon) backend initializes in a fresh
    subprocess within timeout_s.  The axon tunnel can hang indefinitely
    on init when down; probing out-of-process lets us fall back to CPU
    instead of hanging the whole bench run."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.devices(); print('ok')"],
            timeout=timeout_s, capture_output=True, text=True)
        return r.returncode == 0 and "ok" in r.stdout
    except (subprocess.TimeoutExpired, OSError):
        return False


def main():
    import jax

    env_platforms = os.environ.get("JAX_PLATFORMS")
    if env_platforms and jax.config.jax_platforms != env_platforms:
        # axon sitecustomize force-sets jax_platforms; re-honor the env.
        jax.config.update("jax_platforms", env_platforms)
    if str(jax.config.jax_platforms) != "cpu":
        if not _probe_backend():
            print("bench: accelerator backend unreachable; CPU fallback",
                  file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")

    if PROFILE:
        from paddle_tpu.profiler import enable_host_tracer
        enable_host_tracer()

    import jax.numpy as jnp
    from paddle_tpu.distributed.topology import build_mesh
    from paddle_tpu.models import GPTConfig
    from paddle_tpu.models.gpt_spmd import build_spmd_train_step

    backend = jax.default_backend()
    on_tpu = backend not in ("cpu",)
    if on_tpu:
        # BERT-base scale: L=12, D=768, H=12, T=512 (BASELINE config 3)
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512)
        # B=128 saturates the v5e MXU (throughput scales ~linearly with
        # batch up to HBM limits: 16->26, 32->41, 64->53, 128->136 seq/s
        # measured); full per-block remat keeps it memory-feasible
        B, T, steps, dtype = 128, 512, 10, jnp.bfloat16
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                        num_heads=4, max_seq_len=128, ffn_mult=2)
        B, T, steps, dtype = 8, 128, 3, jnp.float32

    mesh = build_mesh({"dp": 1}, devices=jax.devices()[:1])
    # 'ctx' remat saves per-block attention outputs: measured +1-3% over
    # full remat on v5e at this config (see round-2 ablation)
    step, init_fn = build_spmd_train_step(cfg, mesh, compute_dtype=dtype,
                                          remat_policy="ctx" if on_tpu
                                          else "full")
    params, opt_state = init_fn(seed=0)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T)), jnp.int32)

    # warmup/compile — timed: this is the cold-start cost a persistent
    # compilation cache (FLAGS_compile_cache_dir) amortizes across
    # relaunches
    cache_before = _compile_cache_info()
    t_cold = time.perf_counter()
    loss, params, opt_state = step(params, opt_state, ids, labels)
    float(loss)
    jax.block_until_ready(params)
    cold_start_s = time.perf_counter() - t_cold
    cache_warm = _compile_cache_info()

    # best-of-N repetitions: the tunneled chip is shared, so single-window
    # timings vary ~2x with interference; the max is the machine's rate.
    # Batches arrive through the io DevicePrefetcher (the Model.fit input
    # stage) so the measured data_wait is the pipeline's real handoff
    # cost; the arrays are device-resident, so the device_put is free and
    # the leg stays comparable with earlier rounds.
    from paddle_tpu.io import DevicePrefetcher
    reps = 5 if on_tpu else 1
    best_dt = None
    best_wait = 0.0
    for _ in range(reps):
        feed = DevicePrefetcher(iter([(ids, labels)] * steps), depth=2)
        it = iter(feed)
        wait_s = 0.0
        t0 = time.perf_counter()
        for _ in range(steps):
            tw = time.perf_counter()
            bx, by = next(it)
            wait_s += time.perf_counter() - tw
            loss, params, opt_state = step(params, opt_state, bx, by)
        # force full materialization: through the remote tunnel,
        # block_until_ready alone can return before the device finishes
        float(loss)
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        feed.close()
        if best_dt is None or dt < best_dt:
            best_dt, best_wait = dt, wait_s

    seq_per_sec = B * steps / best_dt
    target = 0.8 * 107.0  # see module docstring
    # model FLOPs utilization: fwd+bwd matmul+attention flops only (no
    # remat recompute counted — the standard MFU convention), against
    # peak 197 bf16 TFLOP/s for one v5e chip
    D, L, V = cfg.hidden_size, cfg.num_layers, cfg.vocab_size
    flops_per_tok = 6 * (L * 12 * D * D + D * V) + 12 * L * T * D
    mfu = seq_per_sec * T * flops_per_tok / 197e12
    result = {
        "metric": f"gpt_bert_base_train_seq_per_sec_per_chip[{backend}]"
        if on_tpu else f"gpt_small_train_seq_per_sec[{backend}]",
        "value": round(seq_per_sec, 2),
        "unit": "seq/s",
        "vs_baseline": round(seq_per_sec / target, 3),
        "mfu": round(mfu, 3),
        # async-pipeline attribution: cold start (trace+compile+step 1)
        # vs steady-state step, and the fraction of the timed window the
        # consumer spent waiting on the input pipeline
        "cold_start_s": round(cold_start_s, 3),
        "steady_step_s": round(best_dt / steps, 4),
        "data_wait_frac": round(best_wait / best_dt, 4),
    }
    if cache_before is not None:
        result["compile_cache"] = {
            "dir": cache_before["dir"],
            "entries_before": cache_before["entries"],
            "cold_start_compiles": cache_warm["entries"]
            - cache_before["entries"],
            "steady_state_compiles": _compile_cache_info()["entries"]
            - cache_warm["entries"],
        }
    try:
        result["amp"] = bench_amp(on_tpu)
    except Exception as e:  # the headline metric must still print
        print(f"bench: amp leg failed: {e!r}", file=sys.stderr)
    try:
        result["remat_offload"] = bench_remat_offload(on_tpu)
    except Exception as e:  # the headline metric must still print
        print(f"bench: remat/offload leg failed: {e!r}", file=sys.stderr)
    try:
        result["program_opt"] = bench_program_opt()
    except Exception as e:  # the headline metric must still print
        print(f"bench: program-opt leg failed: {e!r}", file=sys.stderr)
    try:
        result["extra"] = {"resnet50": bench_resnet(on_tpu)}
    except Exception as e:  # the headline metric must still print
        print(f"bench: resnet leg failed: {e!r}", file=sys.stderr)
    if PROFILE:
        try:
            result["metrics"] = _metrics_snapshot()
        except Exception as e:
            print(f"bench: metrics snapshot failed: {e!r}", file=sys.stderr)
    if INT8:
        try:
            result["serving_int8"] = bench_int8(on_tpu)
        except Exception as e:
            print(f"bench: int8 leg failed: {e!r}", file=sys.stderr)
    if PS:
        try:
            result["ps"] = bench_ps()
        except Exception as e:
            print(f"bench: ps leg failed: {e!r}", file=sys.stderr)
    if SERVE:
        try:
            result["serving"] = bench_serving(on_tpu)
        except Exception as e:  # the headline metric must still print
            print(f"bench: serving leg failed: {e!r}", file=sys.stderr)
        try:
            result["serving_decode"] = bench_decode(on_tpu)
        except Exception as e:
            print(f"bench: decode leg failed: {e!r}", file=sys.stderr)
    if "compile_cache" in result:
        store = _artifact_store_info()
        if store is not None:
            # next to cold_start_compiles: how many executables the AOT
            # artifact store served (hits) vs compiled fresh (misses)
            # across ALL legs — a warm relaunch shows misses == 0
            result["compile_cache"]["artifact_store"] = store
    print(json.dumps(result))


def bench_amp(on_tpu: bool):
    """bf16-vs-fp32 ablation of the flagship GPT train step in ONE
    report: the same config, batch and data trained with fp32 compute
    and with bf16 compute over fp32 master weights (the AMP O2
    contract build_spmd_train_step implements), so seq/s, MFU and the
    steady-step ratio are directly comparable.  The loss delta after
    the timed window is the documented bf16 tolerance band."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.topology import build_mesh
    from paddle_tpu.models import GPTConfig
    from paddle_tpu.models.gpt_spmd import build_spmd_train_step

    if on_tpu:
        # headline BERT-base config at half batch: the fp32 comparison
        # leg must fit without remat tricks skewing the ratio
        cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=512)
        B, T, steps = 64, 512, 6
        remat = "ctx"
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                        num_heads=2, max_seq_len=64, ffn_mult=2)
        B, T, steps = 4, 32, 2
        remat = "none"
    mesh = build_mesh({"dp": 1}, devices=jax.devices()[:1])
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, T)),
                         jnp.int32)
    D, L = cfg.hidden_size, cfg.num_layers
    flops_per_tok = 6 * (L * 12 * D * D + D * cfg.vocab_size) \
        + 12 * L * T * D

    out = {"config": {"B": B, "T": T, "steps": steps,
                      "hidden": D, "layers": L}}
    for name, dtype in (("fp32", jnp.float32), ("bf16", jnp.bfloat16)):
        step, init_fn = build_spmd_train_step(
            cfg, mesh, compute_dtype=dtype, remat_policy=remat)
        params, opt_state = init_fn(seed=0)
        loss, params, opt_state = step(params, opt_state, ids, labels)
        float(loss)
        jax.block_until_ready(params)
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, opt_state = step(params, opt_state, ids,
                                           labels)
        lv = float(loss)
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
        sps = B * steps / dt
        out[name] = {"seq_per_sec": round(sps, 2),
                     "mfu": round(sps * T * flops_per_tok / 197e12, 4),
                     "steady_step_s": round(dt / steps, 4),
                     "loss": round(lv, 4)}
    out["bf16_speedup"] = round(
        out["bf16"]["seq_per_sec"]
        / max(out["fp32"]["seq_per_sec"], 1e-9), 3)
    out["loss_delta"] = round(
        abs(out["bf16"]["loss"] - out["fp32"]["loss"]), 4)
    return out


def bench_remat_offload(on_tpu: bool):
    """A train config whose planner-estimated peak EXCEEDS
    ``FLAGS_remat_budget_mb`` training successfully through
    ``Model.fit``'s executing-remat path (the jitted step wraps its
    loss in jax.checkpoint when the static memory plan overshoots the
    budget) with the ``prepare(offload=True)`` opt-state host-offload
    knob engaged (pinned_host where the backend has it; audited no-op
    on CPU)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi.model import Model
    from paddle_tpu.jit import InputSpec

    if on_tpu:
        width, depth, B, steps, budget_mb = 4096, 8, 1024, 4, 64
    else:
        width, depth, B, steps, budget_mb = 512, 4, 256, 2, 2
    paddle.seed(0)
    layers = [nn.Linear(64, width)]
    for _ in range(depth):
        layers += [nn.Tanh(), nn.Linear(width, width)]
    layers += [nn.Tanh(), nn.Linear(width, 16)]
    net = nn.Sequential(*layers)
    m = Model(net, inputs=[InputSpec([None, 64], "float32", name="x")],
              labels=[InputSpec([None], "int64", name="y")])
    import warnings as _w
    with _w.catch_warnings():
        _w.simplefilter("ignore")   # CPU offload no-op warns by design
        m.prepare(paddle.optimizer.Adam(
                      1e-3, parameters=net.parameters()),
                  paddle.nn.CrossEntropyLoss(), offload=True)
        plan = m.static_memory_plan("train", batch_size=B)
        rng = np.random.RandomState(0)
        x = rng.rand(B, 64).astype("float32")
        y = rng.randint(0, 16, (B,)).astype("int64")
        paddle.set_flags({"FLAGS_program_remat": True,
                          "FLAGS_remat_budget_mb": budget_mb})
        try:
            for _ in range(steps):
                logs = m.train_batch([x], [y])
            loss = float(logs["loss"])
        finally:
            paddle.set_flags({"FLAGS_program_remat": False,
                              "FLAGS_remat_budget_mb": 0})
    assert np.isfinite(loss), f"remat+offload leg diverged: {loss}"
    assert plan.peak_bytes > budget_mb * (1 << 20), (
        "config under budget — the leg no longer demonstrates an "
        "over-budget model training")
    assert getattr(m, "_remat_active", False), "remat never engaged"
    offloaded = getattr(m, "_offload_sh_cache", None) is not None
    return {"planner_peak_bytes": int(plan.peak_bytes),
            "budget_mb": budget_mb, "remat_engaged": True,
            "offload": "pinned_host" if offloaded
            else "unavailable (no pinned_host memory space)",
            "steps": steps, "loss": round(loss, 4)}


def bench_ps():
    """BENCH_PS=1: sharded embedding PS under a skewed lookup/update
    workload — 2 replicated shards, a HeterCache in front (the hot-row
    tier), a mid-run primary SIGKILL-analog measuring time-to-recovery
    through the failover path.  Reports lookups/s, pull p50/p99 ms,
    cache hit rate, and recovery-after-host-loss seconds (ROADMAP item
    4's bench contract)."""
    import socket
    import time
    import numpy as np
    from paddle_tpu.distributed.fleet import HeterCache
    from paddle_tpu.distributed.fleet.ps import PSClient, PSServer
    from paddle_tpu.profiler import metrics as pm

    def ep():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return f"127.0.0.1:{port}"

    n_shards, dim, batch, n_batches = 2, 16, 256, 60
    keyspace = 100_000
    pris, reps = [ep() for _ in range(n_shards)], \
        [ep() for _ in range(n_shards)]
    rsrvs = [PSServer(r, shard_id=i, role="replica")
             for i, r in enumerate(reps)]
    psrvs = [PSServer(p, shard_id=i, replicate_to=reps[i])
             for i, p in enumerate(pris)]
    cli = None
    try:
        for s in rsrvs + psrvs:
            s.add_sparse_table("emb", dim, seed=0)
            s.start()
        cli = PSClient(pris, replicas=reps, timeout=5.0, max_tries=2)
        cache = HeterCache(cli, embedding_dim=dim, cache_rows=4096)
        rng = np.random.RandomState(0)
        # zipf-ish skew: hot head + uniform tail, like real id traffic
        hot = rng.randint(0, 2048, (n_batches, batch // 2))
        cold = rng.randint(0, keyspace, (n_batches, batch - batch // 2))
        batches = np.concatenate([hot, cold], axis=1).astype(np.int64)
        cache.pull_sparse("emb", batches[0])      # warm connections
        hist0 = pm.histogram("ps.pull.ms").count
        t0 = time.perf_counter()
        for i in range(n_batches):
            cache.pull_sparse("emb", batches[i])
            if i % 4 == 0:
                cli.push_sparse("emb", batches[i][:64],
                                np.ones((64, dim), np.float32) * 1e-3)
        dt = time.perf_counter() - t0
        hist = pm.histogram("ps.pull.ms")
        lookups = n_batches * batch
        # host loss: flush the staleness window, stop primary 0, and
        # measure time until shard-0 keys serve again via the replica
        cli.flush_replication(10.0)
        shard0_keys = np.arange(0, 2 * n_shards, n_shards,
                                dtype=np.int64)
        psrvs[0].stop()
        t0 = time.perf_counter()
        cli.pull_sparse("emb", shard0_keys)
        recovery_s = time.perf_counter() - t0
        return {
            "shards": n_shards,
            "replicated": True,
            "lookups_per_s": round(lookups / dt, 1),
            "pull_p50_ms": round(hist.percentile(50) or 0.0, 3),
            "pull_p99_ms": round(hist.percentile(99) or 0.0, 3),
            "pull_rpcs": hist.count - hist0,
            "cache_hit_rate": round(
                cache.hits / (cache.hits + cache.misses), 3)
            if (cache.hits + cache.misses) else 0.0,
            "recovery_after_host_loss_s": round(recovery_s, 3),
            "failovers": pm.counter("ps.failover").value,
        }
    finally:
        # a failed leg must not leak servers/pools into the other
        # bench legs measured in this same process
        if cli is not None:
            cli.close()
        for s in psrvs + rsrvs:
            s.stop()


def bench_resnet(on_tpu: bool):
    """ResNet50 imgs/s through the real user API (paddle.Model compiled
    train step) — BASELINE north-star 2.  V100 fp16 ResNet50 ImageNet
    training is ~390 imgs/s (NVIDIA public), target 0.8x = 312."""
    import time
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle

    paddle.seed(0)
    if on_tpu:
        B, hw, steps, nclass = 256, 224, 10, 1000
        net = paddle.vision.models.resnet50(num_classes=nclass)
        amp = "O2"
    else:
        B, hw, steps, nclass = 8, 32, 2, 10
        net = paddle.vision.models.resnet18(num_classes=nclass)
        amp = None
    model = paddle.Model(net)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(),
                  amp_configs=amp)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(B, 3, hw, hw), jnp.float32)
    # int32, not int64: x64 is disabled, so a jnp.int64 request silently
    # truncates with a per-run warning — int32 is what actually lands on
    # device either way
    y = jnp.asarray(rng.randint(0, nclass, (B, 1)), jnp.int32)
    t_cold = time.perf_counter()
    model.train_batch([x], [y])          # compile
    p0 = next(iter(net.parameters()))
    jax.block_until_ready(p0._data)
    float(jnp.sum(p0._data.astype(jnp.float32)))
    cold_start_s = time.perf_counter() - t_cold

    # timed region runs with the host tracer live: _train_batch_jit then
    # records the per-step 'device' (dispatch/backpressure) phase, which
    # together with the manual data-wait split attributes the wall time
    # instead of asserting where it went.  Tracer cost on the compiled
    # path is a handful of clock reads per STEP, not per op.
    from paddle_tpu.io import DevicePrefetcher
    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.profiler import tracer as ptracer
    from paddle_tpu.profiler import memscope as pmem
    dev_ns = pm.counter("train.step.device_ns")
    was_tracing = ptracer.active
    ptracer.enable()
    was_mem = pmem.active
    pmem.enable()
    pmem.set_tag_bytes("params",
                       pmem.tree_nbytes(list(net.parameters())))
    reps = 4 if on_tpu else 1
    best = None
    best_wait = 0.0
    best_dev_ns = 0
    best_goodput = None
    try:
        for _ in range(reps):
            feed = DevicePrefetcher(iter([(x, y)] * steps), depth=2)
            it = iter(feed)
            wait_s = 0.0
            dev0 = dev_ns.value
            logs = None
            gp = pmem.GoodputMeter("bench").start()
            t0 = time.perf_counter()
            for _ in range(steps):
                # loss comes back lazy (hapi _LazyScalar), so
                # consecutive steps pipeline on-device; force full
                # materialization of the final step's params + loss
                # before stopping the clock
                tw = time.perf_counter()
                bx, by = next(it)
                wait_s += time.perf_counter() - tw
                ts = time.perf_counter() if PROFILE else 0
                logs = model.train_batch([bx], [by])
                if PROFILE:
                    pm.histogram("bench.step_latency_ms").observe(
                        (time.perf_counter() - ts) * 1e3)
            # the tail drain is queued device work materializing — it
            # belongs to the device phase, not the host
            t_sync = time.perf_counter()
            float(logs["loss"])
            jax.block_until_ready(p0._data)
            float(jnp.sum(p0._data.astype(jnp.float32)))
            t_end = time.perf_counter()
            dt = t_end - t0
            feed.close()
            gp.add_s("data_wait", wait_s)
            gp.step_ns(int((dt - wait_s) * 1e9))
            gdoc = gp.finish(export=False)
            if best is None or dt < best:
                best, best_wait = dt, wait_s
                best_dev_ns = dev_ns.value - dev0 + \
                    int((t_end - t_sync) * 1e9)
                best_goodput = gdoc
    finally:
        if not was_tracing:
            ptracer.disable()
        if not was_mem:
            pmem.disable()
    imgs = B * steps / best
    # ResNet50 fwd ~4.1 GFLOP/img at 224^2; fwd+bwd ~3x (no remat on
    # the conv path), against one v5e chip's 197 bf16 TFLOP/s peak —
    # conv-path MFU is structurally lower than the transformer's (small
    # channel counts early in the net under-fill the MXU; profiled
    # conv-path table in BASELINE.md)
    mfu = imgs * 3 * 4.1e9 / 197e12
    wait_frac = best_wait / best
    dev_frac = min(1.0, best_dev_ns / 1e9 / best)
    out = {"value": round(imgs, 1), "unit": "imgs/s",
           "vs_baseline": round(imgs / (0.8 * 390.0), 3),
           "mfu": round(mfu, 3),
           "cold_start_s": round(cold_start_s, 3),
           "steady_step_s": round(best / steps, 4),
           "data_wait_frac": round(wait_frac, 4),
           # dispatch/backpressure vs everything-else-on-host split for
           # the best rep — the "where did the step go" attribution
           "device_frac": round(dev_frac, 4),
           "host_frac": round(max(0.0, 1.0 - wait_frac - dev_frac), 4),
           # memscope leg: HBM ceiling + where it went + best-rep
           # goodput (productive fraction of the timed wall)
           "peak_hbm_bytes": pmem.peak_bytes(),
           "mem_bytes_by_tag": pmem.tag_bytes(),
           "goodput_frac": best_goodput["fractions"]["productive"]
           if best_goodput else None}
    try:
        # static planner estimate next to the measured ceiling: the
        # plan covers fwd+bwd (no optimizer slots), so est/measured
        # under Momentum runs a bit low by construction
        from paddle_tpu.jit import InputSpec
        plan = model.static_memory_plan(
            mode="train",
            input_spec=[InputSpec([B, 3, hw, hw], "float32", name="img")],
            label_spec=[InputSpec([B, 1], "int32", name="label")])
        out["static_peak_bytes_est"] = int(plan.peak_bytes)
        if out["peak_hbm_bytes"]:
            out["static_est_over_measured"] = round(
                plan.peak_bytes / out["peak_hbm_bytes"], 3)
    except Exception as e:
        print(f"bench: resnet static memory plan failed: {e!r}",
              file=sys.stderr)
    try:
        # per-phase share of the step (conv/norm/elementwise/optimizer)
        # off the PR 1 tracer op table — same summary path as
        # tools/profile_resnet.py.  MFU-by-phase: phase share x leg MFU.
        shares = _resnet_phase_shares(model, opt, x, y, p0)
        out["phase_shares"] = {k: round(v["time_frac"], 4)
                               for k, v in shares.items()}
        out["phase_mfu"] = {k: round(v["time_frac"] * out["mfu"], 4)
                            for k, v in shares.items()}
    except Exception as e:
        print(f"bench: resnet phase breakdown failed: {e!r}",
              file=sys.stderr)
    try:
        out["fused"] = _resnet_fused_ablation(on_tpu)
    except Exception as e:
        print(f"bench: resnet fused ablation failed: {e!r}",
              file=sys.stderr)
    return out


def _resnet_phase_shares(model, opt, x, y, p0):
    """conv/norm/elementwise/optimizer time shares from the tracer op
    table — the shared ``tracer.eager_phase_profile`` recipe, the same
    one ``tools/profile_resnet.py`` prints, so the two can never
    disagree on methodology."""
    from paddle_tpu.profiler import tracer
    _, shares, _ = tracer.eager_phase_profile(model, opt, x, y, p0)
    return shares


def _resnet_fused_ablation(on_tpu: bool):
    """Measured before/after for the kernel work: the SAME fixed-seed
    fit leg with FLAGS_fused_conv + FLAGS_fused_optimizer both off vs
    both on (cold start incl. trace+compile, steady step, and the eager
    optimizer step where the fused update actually lives)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.utils import flags as fl

    if on_tpu:
        B, hw, steps, nclass, depth = 128, 224, 6, 1000, 50
    else:
        B, hw, steps, nclass, depth = 8, 32, 4, 10, 18

    def leg(fused):
        paddle.seed(0)
        fl.set_flags({"FLAGS_fused_conv": fused,
                      "FLAGS_fused_optimizer": fused})
        net = getattr(paddle.vision.models, f"resnet{depth}")(
            num_classes=nclass)
        model = paddle.Model(net)
        opt = paddle.optimizer.Momentum(
            learning_rate=0.1, momentum=0.9,
            parameters=net.parameters())
        model.prepare(opt, paddle.nn.CrossEntropyLoss())
        rng = np.random.RandomState(0)
        xb = jnp.asarray(rng.rand(B, 3, hw, hw), jnp.float32)
        yb = jnp.asarray(rng.randint(0, nclass, (B, 1)), jnp.int32)
        p0 = next(iter(net.parameters()))
        t0 = time.perf_counter()
        logs = model.train_batch([xb], [yb])
        float(logs["loss"])
        jax.block_until_ready(p0._data)
        cold = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(steps):
            logs = model.train_batch([xb], [yb])
        float(logs["loss"])
        jax.block_until_ready(p0._data)
        steady = (time.perf_counter() - t0) / steps
        # eager optimizer step: where the fused update replaces the
        # per-leaf dispatch loop
        model._train_batch_eager([xb], [yb], update=False)
        g0 = next(p for p in net.parameters() if p.grad is not None)
        jax.block_until_ready(g0.grad._data)
        opt.step()             # group-jit compile outside the clock
        jax.block_until_ready(p0._data)
        model._train_batch_eager([xb], [yb], update=False)
        t0 = time.perf_counter()
        opt.step()
        jax.block_until_ready(p0._data)
        opt_ms = (time.perf_counter() - t0) * 1e3
        opt.clear_grad()
        return cold, steady, opt_ms

    flags_was = fl.get_flags(["FLAGS_fused_conv",
                              "FLAGS_fused_optimizer"])
    # interleaved best-of-N: the shared-CPU/tunneled-chip noise between
    # two sequential single runs is larger than the effect being
    # measured, and leg order must not bias the comparison
    best = {False: None, True: None}
    try:
        for _ in range(3 if not on_tpu else 2):
            for fused in (False, True):
                r = leg(fused)
                if best[fused] is None:
                    best[fused] = list(r)
                else:
                    best[fused] = [min(a, b)
                                   for a, b in zip(best[fused], r)]
    finally:
        fl.set_flags(flags_was)
    cold_off, steady_off, opt_off = best[False]
    cold_on, steady_on, opt_on = best[True]
    return {
        "config": f"resnet{depth} b{B} {hw}x{hw}",
        "cold_start_s": {"off": round(cold_off, 3),
                         "on": round(cold_on, 3)},
        "steady_step_s": {"off": round(steady_off, 4),
                          "on": round(steady_on, 4)},
        "eager_opt_step_ms": {"off": round(opt_off, 2),
                              "on": round(opt_on, 2)},
        "steady_speedup": round(steady_off / steady_on, 3),
        "cold_speedup": round(cold_off / cold_on, 3),
        "opt_step_speedup": round(opt_off / opt_on, 2),
    }


def bench_int8(on_tpu: bool):
    """Int8 serving leg: the SAME resnet artifact served through two
    InferenceEngines — fp32 vs the int8 program variant (per-output-
    channel weight scales, axis-aware) — reporting latency and top-1
    agreement.  Both run the full engine path (bucketing +
    ExecutableCache), so the numbers are endpoint numbers."""
    import tempfile
    import warnings
    import paddle_tpu as paddle
    from paddle_tpu import inference, serving
    from paddle_tpu.jit import InputSpec

    paddle.seed(0)
    if on_tpu:
        B, hw, nclass, depth, reqs = 64, 224, 1000, 50, 24
    else:
        B, hw, nclass, depth, reqs = 8, 32, 10, 18, 8
    net = getattr(paddle.vision.models, f"resnet{depth}")(
        num_classes=nclass)
    net.eval()
    prefix = os.path.join(tempfile.mkdtemp(prefix="bench_int8_"), "m")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        paddle.jit.save(net, prefix, input_spec=[
            InputSpec([B, 3, hw, hw], "float32", name="x")])

    rng = np.random.RandomState(0)
    batches = [rng.rand(B, 3, hw, hw).astype("float32")
               for _ in range(reqs)]

    def serve(precision, name):
        cfg = inference.Config(prefix)
        if precision is not None:
            cfg.set_precision(precision)
        eng = serving.InferenceEngine(cfg, serving.EngineConfig(
            max_batch_size=B, min_batch_bucket=B, num_workers=1,
            name=name))
        eng.infer([batches[0]], timeout=600)      # compile off-clock
        outs, lats = [], []
        for xb in batches:
            t0 = time.perf_counter()
            outs.append(eng.infer([xb], timeout=600)[0])
            lats.append((time.perf_counter() - t0) * 1e3)
        eng.close()
        lats.sort()
        return outs, lats[len(lats) // 2]

    ref, p50_fp32 = serve(None, "bench_fp32")
    q, p50_int8 = serve(inference.PrecisionType.Int8, "bench_int8")
    top1 = [np.argmax(r, axis=1) for r in ref]
    top1_q = [np.argmax(o, axis=1) for o in q]
    agree = float(np.mean([np.mean(a == b)
                           for a, b in zip(top1, top1_q)]))
    rel = float(max(np.abs(np.asarray(b, np.float32)
                           - np.asarray(a, np.float32)).max()
                    / (np.abs(np.asarray(a, np.float32)).max() or 1.0)
                    for a, b in zip(ref, q)))
    return {
        "config": f"resnet{depth} b{B} {hw}x{hw}, {reqs} requests",
        "p50_ms": {"fp32": round(p50_fp32, 2),
                   "int8": round(p50_int8, 2)},
        "speedup": round(p50_fp32 / p50_int8, 3),
        "top1_agreement": round(agree, 4),
        "max_rel_err": round(rel, 5),
    }


def bench_program_opt():
    """Optimizing-pass leg: capture the GPT and ResNet forwards (plus
    the standard serving epilogue a deployment wraps them in —
    temperature-scaled softmax + confidence head, written the naive way
    with the scale recomputed per head) into static Programs, run them
    through CompiledProgram with FLAGS_program_opt off/on, and report
    per-program folded/merged/fused op counts with a bit-exactness
    check against the unoptimized execution.  Backend-independent (the
    pass layer rewrites the op list before any compile), so the config
    stays small on TPU too."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu import static
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.jit.dy2static.program_translator import \
        ProgramTranslator
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.profiler import metrics as pm
    from paddle_tpu.utils import flags as fl

    COUNTERS = ("static.pass.const_folded", "static.pass.cse_merged",
                "static.pass.ops_fused", "static.pass.fusion_groups")

    def measure(name, prog, fetch, feed):
        exe = static.Executor()
        opt_was = fl.get_flags(["FLAGS_program_opt"])
        fl.set_flags({"FLAGS_program_opt": False})
        try:
            t0 = time.perf_counter()
            refs = exe.run(static.CompiledProgram(prog), feed=feed,
                           fetch_list=fetch, use_program_cache=False)
            plain_s = time.perf_counter() - t0
            for k in COUNTERS:
                pm.counter(k).reset()
            fl.set_flags({"FLAGS_program_opt": True})
            comp = static.CompiledProgram(prog)
            optp = comp._optimized_program(
                tuple(getattr(f, "name", f) for f in fetch))
            t0 = time.perf_counter()
            outs = exe.run(comp, feed=feed, fetch_list=fetch,
                           use_program_cache=False)
            opt_s = time.perf_counter() - t0
        finally:
            fl.set_flags(opt_was)
        exact = all(np.array_equal(a, b) for a, b in zip(refs, outs))
        if not exact:
            raise AssertionError(
                f"{name}: FLAGS_program_opt=1 output differs from "
                "FLAGS_program_opt=0")
        # static planner estimate vs memscope-measured replay peak on
        # the same program — the golden-program calibration the memplan
        # gate enforces in CI
        mem = {}
        try:
            from paddle_tpu.static.passes.memory_plan import (
                build_memory_plan, measured_replay)
            plan = build_memory_plan(
                prog,
                feed_shapes={k: tuple(v.shape) for k, v in feed.items()},
                feed_dtypes={k: str(v.dtype) for k, v in feed.items()},
                fetch_names=[getattr(f, "name", f) for f in fetch])
            replay = measured_replay(prog, feed, fetch)
            mem = {"static_peak_bytes_est": int(plan.peak_bytes),
                   "peak_hbm_bytes": int(replay["peak_bytes"]),
                   "static_est_over_measured": round(
                       plan.peak_bytes / max(1, replay["peak_bytes"]), 3)}
        except Exception as e:
            print(f"bench: {name} static memory plan failed: {e!r}",
                  file=sys.stderr)
        return {
            "ops": len(prog.ops), "ops_after": len(optp.ops),
            **mem,
            "const_folded": pm.counter(COUNTERS[0]).value,
            "cse_merged": pm.counter(COUNTERS[1]).value,
            "ops_fused": pm.counter(COUNTERS[2]).value,
            "fusion_groups": pm.counter(COUNTERS[3]).value,
            "bit_exact": exact,
            # cold trace+compile+run wall time either way — the op-list
            # shrink is what the optimizing passes buy
            "cold_run_plain_s": round(plain_s, 3),
            "cold_run_opt_s": round(opt_s, 3),
        }

    paddle.seed(0)
    rng = np.random.RandomState(0)
    pt = ProgramTranslator()

    cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                    num_heads=4, max_seq_len=128, ffn_mult=2)
    gpt = GPT(cfg)
    gpt.eval()

    def gpt_serve(ids):
        logits = gpt.forward(ids)
        temp = paddle.to_tensor(np.float32(0.7))
        inv = 1.0 / temp                     # const-only: folds
        probs = F.softmax(logits * inv, axis=-1)
        conf = paddle.max(F.softmax(logits * inv, axis=-1), axis=-1)
        return probs, conf                   # duplicate scale: cse

    prog, _, fetch = pt.get_program(
        gpt_serve, [InputSpec([4, 64], "int32", name="ids")])
    out = {"gpt": measure(
        "gpt", prog, fetch,
        {"ids": rng.randint(0, cfg.vocab_size, (4, 64)).astype("int32")})}

    resnet = paddle.vision.models.resnet18(num_classes=100)
    resnet.eval()

    def resnet_serve(img):
        logits = resnet.forward(img)
        temp = paddle.to_tensor(np.float32(2.0))
        inv = 1.0 / temp
        probs = F.softmax(logits * inv, axis=-1)
        conf = paddle.max(F.softmax(logits * inv, axis=-1), axis=-1)
        return probs, conf

    prog2, _, fetch2 = pt.get_program(
        resnet_serve, [InputSpec([2, 3, 32, 32], "float32", name="img")])
    out["resnet18"] = measure(
        "resnet18", prog2, fetch2,
        {"img": rng.rand(2, 3, 32, 32).astype("float32")})
    return out


def bench_serving(on_tpu: bool):
    """Serving throughput/latency through the real endpoint path: an
    InferenceEngine (dynamic batching over a cloned-predictor pool,
    paddle_tpu/serving/) hammered by concurrent client threads with
    randomized batch sizes.  Reports req/s and p50/p99 end-to-end
    latency plus batch-occupancy/compile accounting — the serving
    analog of the seq/s training headline."""
    import tempfile
    import threading
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.profiler import metrics as pm

    paddle.seed(0)
    if on_tpu:
        d_in, d_hid, max_batch, clients, per_client = 256, 1024, 32, 16, 64
    else:
        d_in, d_hid, max_batch, clients, per_client = 32, 64, 8, 8, 25
    net = nn.Sequential(nn.Linear(d_in, d_hid), nn.ReLU(),
                        nn.Linear(d_hid, d_in))
    prefix = os.path.join(tempfile.mkdtemp(prefix="bench_serve_"), "m")
    paddle.jit.save(net, prefix, input_spec=[
        InputSpec([-1, d_in], "float32", name="x")])
    engine = serving.InferenceEngine(prefix, serving.EngineConfig(
        max_batch_size=max_batch, batch_timeout_ms=2, num_workers=2,
        max_queue=4 * clients))
    lat = pm.histogram("serving.request.latency_ms")
    occ = pm.histogram("serving.batch.occupancy")
    lat.reset()
    occ.reset()

    # warmup: one request per bucket so compiles land outside the clock
    for b in range(max_batch.bit_length()):
        engine.infer([np.zeros((1 << b, d_in), np.float32)], timeout=300)
    lat.reset()
    occ.reset()

    done = []

    def client(tid):
        rng = np.random.RandomState(tid)
        n = 0
        for _ in range(per_client):
            x = rng.rand(int(rng.randint(1, max_batch // 2 + 1)),
                         d_in).astype("float32")
            try:
                engine.infer([x], timeout=300)
                n += 1
            except serving.RequestRejected:
                pass                       # shed under overload: not lost
        done.append(n)

    from paddle_tpu.profiler import memscope as pmem
    was_mem = pmem.active
    pmem.enable()
    c0 = pmem.compile_count()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    pmem.on_phase("bench")              # one census point at peak load
    compile_s = pmem.compile_seconds(c0)
    engine.close()
    if not was_mem:
        pmem.disable()
    served = sum(done)
    snap = lat.snapshot()
    occ_snap = occ.snapshot()
    compiles = pm.get("serving.compile")
    return {
        "req_per_s": round(served / dt, 1),
        "p50_ms": round(snap.get("p50") or 0.0, 3),
        "p99_ms": round(snap.get("p99") or 0.0, 3),
        "served": served,
        "clients": clients,
        "batch_occupancy_avg": round(occ_snap.get("avg") or 0.0, 2),
        "compiles": compiles.value if compiles else 0,
        "max_batch_size": max_batch,
        "peak_hbm_bytes": pmem.peak_bytes(),
        "mem_bytes_by_tag": pmem.tag_bytes(),
        # wall not burned compiling: serving's goodput analog (the
        # warmup should have left this at 1.0)
        "goodput_frac": round(max(0.0, 1.0 - compile_s / dt), 4),
    }


def bench_decode(on_tpu: bool):
    """Autoregressive serving leg: the continuous-batching
    GenerationEngine (slot scheduler + fixed-capacity KV-cache,
    paddle_tpu/serving + paddle_tpu/generation) under concurrent
    streaming clients with staggered arrivals.  Reports tokens/s,
    time-to-first-token, p50/p99 inter-token latency, and decode batch
    occupancy — the four numbers an LLM chat endpoint is actually
    judged on — next to the one-shot serving numbers."""
    import threading
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.profiler import metrics as pm

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(vocab_size=30528, hidden_size=768,
                        num_layers=12, num_heads=12, max_seq_len=512)
        slots, clients, per_client, max_new = 8, 16, 4, 64
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=4,
                        num_heads=4, max_seq_len=128, ffn_mult=2)
        slots, clients, per_client, max_new = 4, 6, 3, 16
    net = GPT(cfg)
    engine = serving.GenerationEngine(
        net, serving.GenerationEngineConfig(
            max_slots=slots, max_new_tokens=max_new,
            max_queue=4 * clients))
    # warmup: one request per prompt bucket so compiles land outside
    # the clock (same discipline as the one-shot serving leg)
    for pb in serving.seq_buckets(engine.max_length,
                                  engine.config.prompt_bucket_min):
        if pb >= engine.max_length:
            break
        engine.generate(np.ones((min(pb, engine.max_length - max_new
                                     - 1),), np.int32),
                        max_new_tokens=2, timeout=600)
    for h in ("ttft_ms", "inter_token_ms", "decode.occupancy",
              "prefill", "decode"):
        m = pm.get(f"serving.{h}")
        if m is not None:
            m.reset()

    done_tokens = []

    def client(tid):
        rng = np.random.RandomState(200 + tid)
        n = 0
        for r in range(per_client):
            time.sleep(0.002 * tid)        # staggered arrivals
            plen = int(rng.randint(4, min(33, engine.max_length
                                          - max_new - 1)))
            prompt = rng.randint(1, cfg.vocab_size,
                                 (plen,)).astype(np.int32)
            try:
                out = engine.generate(
                    prompt, do_sample=True, temperature=0.8,
                    top_p=0.95, seed=tid * 100 + r, timeout=600)
                n += len(out)
            except serving.RequestRejected:
                pass                       # shed under overload
        done_tokens.append(n)

    from paddle_tpu.profiler import memscope as pmem
    was_mem = pmem.active
    pmem.enable()
    engine._note_memory_tags()
    c0 = pmem.compile_count()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    pmem.on_phase("bench")              # one census point at peak load
    compile_s = pmem.compile_seconds(c0)
    mem_peak = pmem.peak_bytes()
    mem_tags = pmem.tag_bytes()
    engine.close()
    if not was_mem:
        pmem.disable()
    generated = sum(done_tokens)
    ttft = pm.get("serving.ttft_ms").snapshot()
    itl = pm.get("serving.inter_token_ms").snapshot()
    occ = pm.get("serving.decode.occupancy").snapshot()
    compiles = pm.get("serving.compile")
    result = {
        "tokens_per_s": round(generated / dt, 1),
        "ttft_p50_ms": round(ttft.get("p50") or 0.0, 3),
        "ttft_p99_ms": round(ttft.get("p99") or 0.0, 3),
        "inter_token_p50_ms": round(itl.get("p50") or 0.0, 3),
        "inter_token_p99_ms": round(itl.get("p99") or 0.0, 3),
        "decode_occupancy_avg": round(occ.get("avg") or 0.0, 2),
        "decode_occupancy_max": occ.get("max"),
        "tokens_generated": generated,
        "tokens_per_request": max_new,
        "slots": slots,
        "clients": clients,
        "compiles": compiles.value if compiles else 0,
        "peak_hbm_bytes": mem_peak,
        "mem_bytes_by_tag": mem_tags,
        "goodput_frac": round(max(0.0, 1.0 - compile_s / dt), 4),
    }
    try:
        result["paged"] = bench_paged_decode(net, cfg, on_tpu)
    except Exception as e:  # noqa: BLE001 — additive leg, stay loud
        print(f"bench: paged decode leg failed: {e!r}",
              file=sys.stderr)
    try:
        result["disagg"] = bench_disagg(net, cfg, on_tpu)
    except Exception as e:  # noqa: BLE001 — additive leg, stay loud
        print(f"bench: disagg leg failed: {e!r}", file=sys.stderr)
    return result


def bench_paged_decode(net, cfg, on_tpu: bool):
    """Paged-KV serving-memory leg (PR 11): the PagedGenerationEngine
    on a FIXED KV HBM budget — the worst-case footprint of just
    ``base_slots`` contiguous slots — serving many more concurrent
    streams than that budget's per-slot baseline could hold.  Reports
    the numbers this subsystem is judged on: concurrent streams at
    fixed HBM (measured peak decode occupancy vs the baseline slot
    count), KV bytes/token (float32 and int8 storage), prefix-cache
    hit rate on a shared-system-prompt workload, and the speculative
    accept rate — alongside tokens/s + TTFT so serving PRs stay
    machine-comparable end to end."""
    import threading
    from paddle_tpu import serving
    from paddle_tpu.profiler import metrics as pm

    block_size = 16
    base_slots = 2                       # the per-slot HBM baseline
    if on_tpu:
        slots, clients, per_client, max_new = 16, 16, 3, 48
        tail_lo, tail_hi = 4, 17
    else:
        slots, clients, per_client, max_new = 6, 8, 3, 16
        tail_lo, tail_hi = 4, 9
    max_len = int(net.cfg.max_seq_len)
    # the fixed budget: exactly what base_slots worst-case contiguous
    # slots would pin, carved into blocks the pool shares
    num_blocks = base_slots * (max_len // block_size)
    engine = serving.PagedGenerationEngine(
        net, serving.GenerationEngineConfig(
            max_slots=slots, max_new_tokens=max_new,
            max_queue=4 * clients, block_size=block_size,
            num_blocks=num_blocks,
            prefix_cache_blocks=max(2, num_blocks // 4),
            speculative_k=2, name="paged",
            # compile every suffix-bucket chunk + decode + verify
            # executable at construction — the contiguous leg warms
            # all ITS buckets too, so the side-by-side TTFT/tokens_s
            # numbers stay compile-free on both sides
            warmup=True))
    # one block of shared system prompt: every request after the first
    # should hit the prefix cache for it
    sys_prompt = (np.arange(block_size, dtype=np.int32)
                  % (cfg.vocab_size - 1)) + 1
    # warmup the executables outside the clock, then zero the meters
    engine.generate(sys_prompt, max_new_tokens=2, timeout=600)
    for name in ("paged.ttft_ms", "paged.inter_token_ms",
                 "paged.decode.occupancy", "paged.prefill",
                 "paged.decode", "paged.prefix_cache.hit",
                 "paged.prefix_cache.miss",
                 "paged.prefix_cache.hit_tokens", "paged.spec.proposed",
                 "paged.spec.accepted", "paged.tokens_out"):
        m = pm.get(name)
        if m is not None:
            m.reset()

    done_tokens, sheds = [], []

    def client(tid):
        rng = np.random.RandomState(300 + tid)
        n = 0
        for r in range(per_client):
            time.sleep(0.002 * tid)      # staggered arrivals
            tail = rng.randint(
                1, cfg.vocab_size,
                (int(rng.randint(tail_lo, tail_hi)),)).astype(np.int32)
            prompt = np.concatenate([sys_prompt, tail])
            try:
                out = engine.generate(
                    prompt, do_sample=True, temperature=0.8,
                    top_p=0.95, seed=tid * 100 + r, timeout=600)
                n += len(out)
            except serving.RequestRejected:
                sheds.append(tid)        # pool exhausted: typed shed
        done_tokens.append(n)

    from paddle_tpu.profiler import memscope as pmem
    was_mem = pmem.active
    pmem.enable()
    engine._note_memory_tags()
    c0 = pmem.compile_count()
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    pmem.on_phase("bench")              # one census point at peak load
    compile_s = pmem.compile_seconds(c0)
    mem_peak = pmem.peak_bytes()
    mem_tags = pmem.tag_bytes()
    mem_breakdown = engine.memory_breakdown()
    engine.close()
    if not was_mem:
        pmem.disable()
    generated = sum(done_tokens)
    ttft = pm.get("paged.ttft_ms").snapshot()
    occ = pm.get("paged.decode.occupancy").snapshot()
    hits = pm.get("paged.prefix_cache.hit").value
    misses = pm.get("paged.prefix_cache.miss").value
    hit_tokens = pm.get("paged.prefix_cache.hit_tokens").value
    proposed = pm.get("paged.spec.proposed").value
    accepted = pm.get("paged.spec.accepted").value
    peak = int(occ.get("max") or 0)
    H = cfg.num_heads
    D = cfg.hidden_size // H
    f32_per_tok = cfg.num_layers * 2 * H * D * 4
    int8_per_tok = cfg.num_layers * (2 * H * D + 2 * H * 4)
    return {
        "tokens_per_s": round(generated / dt, 1),
        "ttft_p50_ms": round(ttft.get("p50") or 0.0, 3),
        "ttft_p99_ms": round(ttft.get("p99") or 0.0, 3),
        "tokens_generated": generated,
        # the headline: concurrent streams on the SAME KV HBM budget
        # that holds only base_slots worst-case contiguous slots
        "slots_at_fixed_hbm": {
            "kv_budget_blocks": num_blocks,
            "kv_budget_bytes": num_blocks
            * engine.pool.block_bytes,
            "baseline_slots": base_slots,
            "paged_peak_concurrent": peak,
            "multiplier": round(peak / base_slots, 2),
        },
        "kv_bytes_per_token_f32": f32_per_tok,
        "kv_bytes_per_token_int8": int8_per_tok,
        "prefix_hit_rate": round(hits / (hits + misses), 3)
        if (hits + misses) else 0.0,
        "prefix_hit_tokens": hit_tokens,
        "spec_accept_rate": round(accepted / proposed, 3)
        if proposed else 0.0,
        "spec_proposed": proposed,
        "spec_accepted": accepted,
        "requests_shed_kv": len(sheds),
        "block_size": block_size,
        "clients": clients,
        "compiles": pm.get("paged.compile").value
        if pm.get("paged.compile") else 0,
        "peak_hbm_bytes": mem_peak,
        "mem_bytes_by_tag": mem_tags,
        "mem_breakdown": mem_breakdown,
        "goodput_frac": round(max(0.0, 1.0 - compile_s / dt), 4),
    }


def bench_disagg(net, cfg, on_tpu: bool):
    """Disaggregated prefill/decode leg (PR 19): the same shared-head
    workload served two ways — a prefill PagedGenerationEngine that
    exports each prompt's KV chain over the ``kv_wire`` blob format
    into a decode engine (the 2-chip disaggregated split), vs one
    monolithic engine (1 chip).  Reports TTFT p50/p99 and tokens/s
    per chip side by side, plus the wire cost the split pays for its
    role specialization: bytes per transferred chain and the share of
    wall-clock spent in transfer+adopt."""
    from paddle_tpu import serving

    block_size = 16
    if on_tpu:
        max_new, n_req = 48, 15
        tail_lo, tail_hi = 4, 17
    else:
        max_new, n_req = 16, 9
        tail_lo, tail_hi = 4, 9
    max_len = int(net.cfg.max_seq_len)
    num_blocks = 4 * (max_len // block_size)

    def mk(name):
        return serving.PagedGenerationEngine(
            net, serving.GenerationEngineConfig(
                max_slots=4, max_new_tokens=max_new,
                block_size=block_size, num_blocks=num_blocks,
                prefix_cache_blocks=max(2, num_blocks // 2),
                warmup="off", name=name))
    pre, dec, mono = mk("dgpre"), mk("dgdec"), mk("dgmono")
    # 3 distinct 2-block shared heads so the decode engine pulls 3
    # cold chains over the wire; tails vary per request
    heads = [(np.arange(2 * block_size, dtype=np.int32) + 1 + 7 * h)
             % (cfg.vocab_size - 1) + 1 for h in range(3)]
    rng = np.random.RandomState(97)
    prompts = [np.concatenate([heads[i % 3], rng.randint(
        1, cfg.vocab_size,
        (int(rng.randint(tail_lo, tail_hi)),)).astype(np.int32)])
        for i in range(n_req)]
    kws = [dict(do_sample=True, temperature=0.8, top_p=0.95,
                seed=500 + i) for i in range(n_req)]
    for e in (pre, dec, mono):
        # compiles land outside the clock; drop the warmup chain so
        # the decode side starts cold and actually pulls over the wire
        e.generate(prompts[0], max_new_tokens=2, timeout=600)
        e.prefix_cache.clear()

    transfer = {"bytes": 0, "chains": 0, "s": 0.0}

    def disagg_flow(p):
        # what DisaggClient.ensure_chain does, minus the HTTP hop:
        # probe locally, prefill remotely, ship the chain, adopt it
        chain, covered = dec.prefix_cache.lookup(p)
        if chain:
            dec.pool.decref(chain)
        if len(p) - covered <= block_size:
            return
        blob = pre.export_prefix_chain(p)
        if blob is None:
            pre.generate(p, max_new_tokens=1, do_sample=False,
                         timeout=600)
            blob = pre.export_prefix_chain(p)
        t = time.perf_counter()
        dec.import_prefix_chain(blob)
        transfer["s"] += time.perf_counter() - t
        transfer["bytes"] += len(blob)
        transfer["chains"] += 1

    def run(engine, flow):
        ttfts, n = [], 0
        t0 = time.perf_counter()
        for p, kw in zip(prompts, kws):
            r0 = time.perf_counter()
            flow(p)
            first = None
            toks = 0
            for _tok in engine.submit(p, max_new_tokens=max_new,
                                      **kw):
                if first is None:
                    first = time.perf_counter() - r0
                toks += 1
            ttfts.append(first)
            n += toks
        return time.perf_counter() - t0, ttfts, n

    def pct(vals, q):
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    try:
        d_dt, d_ttft, d_n = run(dec, disagg_flow)
        m_dt, m_ttft, m_n = run(mono, lambda p: None)
    finally:
        for e in (pre, dec, mono):
            e.close()

    def side(dt, ttft, n, chips):
        return {
            "chips": chips,
            "ttft_p50_ms": round(pct(ttft, 0.50) * 1e3, 3),
            "ttft_p99_ms": round(pct(ttft, 0.99) * 1e3, 3),
            "tokens_per_s": round(n / dt, 1),
            "tokens_per_s_per_chip": round(n / dt / chips, 1),
            "tokens_generated": n,
        }
    return {
        "requests": n_req,
        "block_size": block_size,
        "disagg": dict(side(d_dt, d_ttft, d_n, 2), **{
            "chains_transferred": transfer["chains"],
            "transfer_bytes_per_chain": transfer["bytes"]
            // max(1, transfer["chains"]),
            "transfer_time_share": round(transfer["s"] / d_dt, 4),
        }),
        "monolithic": side(m_dt, m_ttft, m_n, 1),
    }


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""CI gate for the fault-tolerant sharded PS (ISSUE 15).

Leg 1 — kill-one-shard failover: a 2-shard CTR-tower training run
(hash -> PS embedding -> cvm -> data_norm -> logistic loss) against
primary+replica pairs, with shard 0's primary running as a REAL
subprocess.  Mid-training the driver closes the replication staleness
window and SIGKILLs that primary while a ``ps.pull:fail@N`` chaos spec
injects one extra transport reset.  Asserts EXACT counts — 1 injected
reset, 2 bounded retries (1 chaos + 1 kill), 1 failover, 1 promotion —
plus bit-exact loss parity with an uninterrupted reference run and
bit-exact final embedding rows (zero lost updates), then verifies the
pools/tables wind down leak-free (no surviving non-daemon threads, no
pending replication).

Leg 2 — elastic reshard: a table checkpointed at 4 shards (verified
manifest-v2 commits) reloads onto 2 servers with row-union parity (no
dup/drop, per-row bit-exact pulls).

Wired into tools/run_all_tests.sh.
"""
import os
import signal
import socket
import subprocess
import sys
import tempfile
import textwrap
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SERVER = """
import sys
from paddle_tpu.distributed.fleet.ps import PSServer
ep, shard_id, replicate_to = sys.argv[1], int(sys.argv[2]), sys.argv[3]
srv = PSServer(ep, shard_id=shard_id,
               replicate_to=replicate_to or None)
srv.add_sparse_table("emb", 3, seed=0)
srv.run()
"""


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def ep():
    return f"127.0.0.1:{free_port()}"


def wait_ready(endpoint, deadline=20.0):
    """Raw-socket readiness probe — deliberately NOT the failover-aware
    client path: a ping racing server startup must not promote the
    replica before the run even begins."""
    from paddle_tpu.distributed.fleet.ps import _recv_msg, _send_msg
    host, port = endpoint.rsplit(":", 1)
    t0 = time.monotonic()
    while True:
        try:
            s = socket.create_connection((host, int(port)), timeout=1.0)
            try:
                _send_msg(s, ("ping",))
                assert _recv_msg(s) == ("ok", "pong")
                return
            finally:
                s.close()
        except Exception:
            if time.monotonic() - t0 > deadline:
                raise
            time.sleep(0.05)


def ctr_tower_run(client, n_steps=6, kill_at=None, on_kill=None):
    """Deterministic CTR training loop; returns (losses, final rows)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.distributed.fleet import DistributedEmbedding
    from paddle_tpu.distributed.fleet.ps import Communicator
    from paddle_tpu.ops import ctr

    paddle.seed(0)
    comm = Communicator(client, mode="sync")
    emb = DistributedEmbedding("emb", 100, 3, comm)
    rng = np.random.RandomState(0)
    raw_ids = rng.randint(0, 1 << 40, (8, 1)).astype(np.int64)
    buckets = ctr.hash_op(raw_ids, hash_size=100)
    flat = paddle.reshape(paddle.Tensor(buckets._data), [8])
    touched = np.unique(np.asarray(flat._data)).astype(np.int64)
    losses = []
    for step in range(n_steps):
        e = paddle.reshape(emb(paddle.reshape(flat, [8, 1])), [8, 3])
        show_clk = paddle.to_tensor(
            np.abs(rng.rand(8, 2)).astype("float32"))
        x = ctr.continuous_value_model(
            paddle.concat([show_clk, e], axis=1), show_clk, True)
        ones = paddle.to_tensor(np.ones(5, np.float32))
        x, _, _ = ctr.data_norm(x, ones * 2, ones, ones * 2)
        logit = paddle.sum(x, axis=1)
        label = paddle.to_tensor(
            (np.asarray(flat._data) % 2).astype("float32"))
        loss = paddle.mean(
            paddle.nn.functional.binary_cross_entropy_with_logits(
                logit, label))
        loss.backward()
        losses.append(float(loss))
        if kill_at is not None and step == kill_at:
            on_kill()
    rows = client.pull_sparse("emb", touched)
    comm.stop()
    return losses, rows


def counter(name):
    from paddle_tpu.profiler import metrics
    m = metrics.get(name)
    return m.value if m is not None else 0


def leg_failover():
    import numpy as np
    from paddle_tpu.distributed.fleet.ps import PSClient, PSServer
    from paddle_tpu.profiler import flight
    from paddle_tpu.utils import chaos

    # -- uninterrupted reference ------------------------------------------
    ref_eps = [ep(), ep()]
    ref_srvs = [PSServer(e, shard_id=i).start()
                for i, e in enumerate(ref_eps)]
    for s in ref_srvs:
        s.add_sparse_table("emb", 3, seed=0)
    ref_cli = PSClient(ref_eps, timeout=5.0, max_tries=2)
    ref_losses, ref_rows = ctr_tower_run(ref_cli)
    ref_cli.close()
    for s in ref_srvs:
        s.stop()

    # -- victim: shard 0's primary is a real subprocess --------------------
    p0, p1, r0, r1 = ep(), ep(), ep(), ep()
    rep_srvs = [PSServer(r0, shard_id=0, role="replica"),
                PSServer(r1, shard_id=1, role="replica")]
    pri1 = PSServer(p1, shard_id=1, replicate_to=r1)
    for s in rep_srvs + [pri1]:
        s.add_sparse_table("emb", 3, seed=0)
        s.start()
    script = os.path.join(tempfile.mkdtemp(prefix="ps_gate_"),
                          "server.py")
    with open(script, "w") as f:
        f.write(textwrap.dedent(SERVER))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               PALLAS_AXON_POOL_IPS="")
    proc = subprocess.Popen([sys.executable, script, p0, "0", r0],
                            env=env)
    wait_ready(p0)
    wait_ready(p1)
    cli = PSClient([p0, p1], replicas=[r0, r1], timeout=5.0, max_tries=2)

    flight.clear()
    base_threads = {t for t in threading.enumerate() if not t.daemon}
    retries0 = counter("resilience.retry")
    # configure() resets per-site counters; @3 lands on a live-shard
    # pull attempt during step 1 (2 attempts per training step)
    chaos.configure("ps.pull:fail@3")

    def kill():
        # close the bounded-staleness window, then the real SIGKILL
        assert cli.flush_replication(10.0), "replication flush timed out"
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=10)

    try:
        losses, rows = ctr_tower_run(cli, kill_at=2, on_kill=kill)
    finally:
        chaos.reset()

    injected = counter("chaos.injected.ps.pull")
    failovers = counter("ps.failover")
    promotes = counter("ps.promote")
    retries = counter("resilience.retry") - retries0
    assert injected == 1, f"injected resets: {injected} != 1"
    assert failovers == 1, f"failovers: {failovers} != 1"
    assert promotes == 1, f"promotions: {promotes} != 1"
    assert retries == 2, f"bounded retries: {retries} != 2 " \
        f"(1 chaos + 1 kill-path)"
    view = cli.shard_views[0]
    assert view.promoted and view.primary == r0
    assert cli._shard_call(0, ("role",)) == "primary"
    fc = flight.counts()
    assert fc.get("ps.failover") == 1 and fc.get("ps.promote") == 1, fc
    assert losses == ref_losses, \
        f"loss trajectory diverged:\n{losses}\nvs\n{ref_losses}"
    assert np.array_equal(rows, ref_rows), "lost updates after failover"
    # promoted shard keeps serving writes
    cli.push_sparse("emb", np.arange(4, dtype=np.int64),
                    np.ones((4, 3), np.float32))

    # -- leak-free teardown ------------------------------------------------
    st = cli._shard_call(1, ("repl_stats",))
    assert st["pending"] == 0 and st["dropped"] == 0, st
    cli.close()
    for s in rep_srvs + [pri1]:
        s.stop()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        alive = {t for t in threading.enumerate()
                 if not t.daemon} - base_threads
        if not alive:
            break
        time.sleep(0.05)
    assert not alive, f"leaked non-daemon threads: {alive}"
    return {"injected": injected, "failovers": failovers,
            "promotes": promotes, "retries": retries,
            "final_loss": losses[-1]}


def leg_reshard():
    import numpy as np
    from paddle_tpu.distributed.fleet.ps import (AdagradSGDRule, PSClient,
                                                 PSServer)

    def cluster(n):
        eps = [ep() for _ in range(n)]
        srvs = [PSServer(e, shard_id=i, n_shards=n).start()
                for i, e in enumerate(eps)]
        for s in srvs:
            s.add_sparse_table("emb", 4, rule=AdagradSGDRule(0.1),
                               seed=11)
        return eps, srvs

    root = os.path.join(tempfile.mkdtemp(prefix="ps_gate_"), "ckpt")
    keys = np.arange(128, dtype=np.int64)
    rng = np.random.RandomState(2)
    eps4, srvs4 = cluster(4)
    cli4 = PSClient(eps4, timeout=5.0)
    for _ in range(5):
        cli4.push_sparse("emb", keys, rng.randn(128, 4).astype(np.float32))
    ref = cli4.pull_sparse("emb", keys)
    cli4.save_state(root, step=5)
    cli4.close()
    for s in srvs4:
        s.stop()

    eps2, srvs2 = cluster(2)
    cli2 = PSClient(eps2, timeout=5.0)
    cli2.load_state(root, reshard_ps=2)      # verified + resharded
    out = cli2.pull_sparse("emb", keys)
    assert np.array_equal(ref, out), "resharded rows not bit-exact"
    per = [sorted(srvs2[i]._tables["emb"]._rows) for i in range(2)]
    union = sorted(k for p in per for k in p)
    assert union == sorted(keys.tolist()), "row union broken (dup/drop)"
    assert all(k % 2 == i for i, p in enumerate(per) for k in p)
    cli2.close()
    for s in srvs2:
        s.stop()
    return {"rows": len(union), "src_shards": 4, "dst_shards": 2}


def main():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    r1 = leg_failover()
    r2 = leg_reshard()
    print(f"ps gate OK: failover leg {r1}; reshard leg {r2}")
    return 0


if __name__ == "__main__":
    sys.exit(main())

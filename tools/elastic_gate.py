#!/usr/bin/env python
"""CI elastic gate: degrade-and-continue, end to end, on CPU.

Leg 1 — elastic relaunch at the surviving world size: launch 4
supervised workers (``--supervise --np 2:4``), SIGKILL one mid-step,
and assert the gang re-forms at world 3 via a rendezvous round, resumes
from the newest intact checkpoint (not step 0), reshards a DP-sharded
optimizer-state tree saved on the old 4-way mesh onto the surviving
3-way mesh bit-exactly, spends ZERO restart budget (shrinks are
degradation, not failure), and finishes with the final loss matching an
uninterrupted single-process reference run.  Exact ``launch.restarts``
/ rendezvous-round counts are pinned.

Leg 2 — straggler detection + eviction: 2 workers with ``host.slow``
armed on rank 1 (deterministic chaos delay each step) under
``--evict_stragglers``; the supervisor must flag rank 1 at exactly
``FLAGS_straggler_patience`` strikes, evict it via a rendezvous
denylist entry, and re-form at world 1 to completion.

Wired into tools/run_all_tests.sh.
"""
import json
import os
import subprocess
import sys
import tempfile
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:         # the in-process reference run imports
    sys.path.insert(0, REPO)     # the framework from the source tree
os.environ.setdefault("JAX_PLATFORMS", "cpu")

ELASTIC_TRAINER = """
import json, os, signal
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.distributed.parallel import mesh_for_world
from paddle_tpu.hapi.callbacks import Callback

rank = os.environ["PADDLE_TRAINER_ID"]
world = int(os.environ["PADDLE_TRAINERS_NUM"])
gen = int(os.environ.get("PADDLE_RESTART_GENERATION", "0"))
work = os.environ["ELASTIC_GATE_DIR"]

with open(os.path.join(work, f"world_g{gen}_r{rank}"), "w") as f:
    f.write(str(world))

# cross-world resharding of DP-sharded state, inside the degraded gang:
# generation 0 saves a ZeRO-style sharded tree on the 4-way local mesh;
# the surviving generation restores it onto its 3-way mesh and demands
# bit parity.
import jax, jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
shard_path = os.path.join(work, "sharded_opt")
if rank == "0" and gen == 0:
    mesh = mesh_for_world(4)
    tree = {"m": jax.device_put(jnp.arange(12.0) * 0.5,
                                NamedSharding(mesh, P("dp"))),
            "v": jax.device_put(jnp.arange(24.0).reshape(12, 2),
                                NamedSharding(mesh, P("dp")))}
    ckpt.save_state(shard_path, tree, step=0)
if rank == "0" and gen == 1:
    mesh = mesh_for_world(world)
    back = ckpt.load_state(shard_path, reshard_mesh=mesh, verify=True)
    np.testing.assert_array_equal(np.asarray(back["m"]),
                                  np.arange(12.0) * 0.5)
    np.testing.assert_array_equal(np.asarray(back["v"]),
                                  np.arange(24.0).reshape(12, 2))
    assert back["m"].sharding.spec == P("dp")
    assert len(back["m"].sharding.mesh.devices.flat) == world
    with open(os.path.join(work, "reshard_ok"), "w") as f:
        f.write("1")

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.Tanh(),
                           paddle.nn.Linear(8, 1))
model = paddle.Model(net)
opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
model.prepare(opt, paddle.nn.MSELoss())


class DS(paddle.io.Dataset):
    def __getitem__(self, i):
        import time
        time.sleep(0.1)      # pace steps so async commits land between
        rng = np.random.RandomState(i)
        x = rng.rand(4).astype("float32")
        return x, (x.sum(keepdims=True) * 0.5).astype("float32")

    def __len__(self):
        return 40            # batch 4 -> 10 global steps


class Chronicle(paddle.hapi.callbacks.Callback):
    def on_train_batch_end(self, step, logs=None):
        if rank == "0":
            with open(os.path.join(work, "losses.jsonl"), "a") as f:
                f.write(json.dumps({"step": step, "gen": gen,
                                    "loss": float(logs["loss"])}) + "\\n")
        if rank == "1" and gen == 0 and step >= 2:
            # die MID-step-stream, but only once the chronicler rank
            # has demonstrably trained past step 5 (its per-step
            # commits then exist to resume from) — rank startup cost is
            # not uniform, so a fixed kill step can fire before slower
            # ranks have even begun.  Block here until rank 0 gets
            # there (the watchdog allows 60s of stall).
            import time
            for _ in range(400):
                try:
                    with open(os.path.join(work, "losses.jsonl")) as f:
                        rows = [json.loads(line) for line in f]
                    if rows and max(r_["step"] for r_ in rows) >= 5:
                        os.kill(os.getpid(), signal.SIGKILL)  # lost host
                except OSError:
                    pass
                time.sleep(0.1)


# pay orbax/tensorstore's first-write init (~2s) OUTSIDE the step
# stream so per-step async commits land at their steady cadence and the
# kill finds intact checkpoints to resume from
ckpt.save_state(os.path.join(work, f"warmup_{rank}_g{gen}"),
                {"x": np.zeros(2, np.float32)})

ckptr = ckpt.AsyncCheckpointer(os.path.join(work, f"ckpt_{rank}"),
                               max_to_keep=3)
model.fit(DS(), batch_size=4, epochs=1, verbose=0, shuffle=False,
          checkpointer=ckptr, callbacks=[Chronicle()])
ckptr.close()
"""

STRAGGLER_TRAINER = """
import os
import numpy as np
import paddle_tpu as paddle

rank = os.environ["PADDLE_TRAINER_ID"]
gen = int(os.environ.get("PADDLE_RESTART_GENERATION", "0"))
work = os.environ["ELASTIC_GATE_DIR"]

if rank == "1" and gen == 0:
    # deterministic straggler: every step of THIS rank pays the
    # host.slow delay in the fit loop
    paddle.set_flags({"FLAGS_chaos_spec": "host.slow:delay=0.4"})

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.Tanh(),
                           paddle.nn.Linear(8, 1))
model = paddle.Model(net)
opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
model.prepare(opt, paddle.nn.MSELoss())


class DS(paddle.io.Dataset):
    def __getitem__(self, i):
        import time
        time.sleep(0.05)     # keep the healthy rank busy past eviction
        rng = np.random.RandomState(i)
        x = rng.rand(4).astype("float32")
        return x, (x.sum(keepdims=True) * 0.5).astype("float32")

    def __len__(self):
        return 60            # batch 4 -> 15 steps


model.fit(DS(), batch_size=4, epochs=1, verbose=0, shuffle=False,
          prefetch_to_device=0)
with open(os.path.join(work, f"done_g{gen}_r{rank}"), "w") as f:
    f.write("1")
"""


def _run_leg(work, trainer_body, launch_args, extra_env):
    trainer = os.path.join(work, "trainer.py")
    with open(trainer, "w") as f:
        f.write(textwrap.dedent(trainer_body))
    report = os.path.join(work, "report.json")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO,
               ELASTIC_GATE_DIR=work,
               PADDLE_HEARTBEAT_INTERVAL="0.05",
               PADDLE_SUPERVISE_REPORT=report)
    env.update(extra_env)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--supervise", *launch_args, trainer],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        print(r.stdout[-3000:], file=sys.stderr)
        print(r.stderr[-3000:], file=sys.stderr)
        raise SystemExit(f"elastic gate: launch failed rc={r.returncode}")
    return json.load(open(report)), r


def leg_elastic_relaunch():
    work = tempfile.mkdtemp(prefix="elastic_gate_")
    rep, r = _run_leg(
        work, ELASTIC_TRAINER,
        ["--nproc", "4", "--np", "2:4", "--max_restarts", "2",
         "--devices_per_proc", "4"], {})

    # exact relaunch accounting: ONE shrink-relaunch, zero budget spent
    assert rep["kind"] == "done", rep
    assert rep["restarts"] == 0, rep          # shrink != failure
    assert rep["shrinks"] == 1, rep
    assert rep["restarts_metric"] == 1, rep   # launch.restarts counts it
    assert rep["world"] == 3, rep
    assert rep["world_history"] == [4, 3], rep
    assert rep["rendezvous_rounds"] == 2, rep  # one per gang formation
    assert rep["generation"] == 1, rep

    # the surviving generation ran at world 3 end to end
    for rnk in range(3):
        path = os.path.join(work, f"world_g1_r{rnk}")
        assert os.path.exists(path), f"missing {path}"
        assert open(path).read() == "3"
    assert not os.path.exists(os.path.join(work, "world_g1_r3"))

    # resumed from the newest intact checkpoint, with the DP-sharded
    # side tree resharded 4 -> 3 bit-exactly inside the degraded gang
    assert os.path.exists(os.path.join(work, "reshard_ok"))
    rows = [json.loads(line) for line in
            open(os.path.join(work, "losses.jsonl"))]
    final = {}
    for row in rows:
        final[row["step"]] = row["loss"]
    assert sorted(final) == list(range(10)), sorted(final)
    # the relaunched chronicler resumed from an INTACT commit, not from
    # scratch (>= 1: on a 2-core CI box 4 contending workers commit
    # slower than they step, so the newest intact step may trail the
    # kill step; the slow-tier 2-worker parity test pins >= 2)
    gen1_steps = [row["step"] for row in rows if row["gen"] == 1]
    assert gen1_steps and min(gen1_steps) >= 1, gen1_steps

    # final-loss parity vs an uninterrupted in-process reference
    import paddle_tpu as paddle
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.Tanh(),
                               paddle.nn.Linear(8, 1))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
    model.prepare(opt, paddle.nn.MSELoss())

    import numpy as np

    class DS(paddle.io.Dataset):
        def __getitem__(self, i):
            rng = np.random.RandomState(i)
            x = rng.rand(4).astype("float32")
            return x, (x.sum(keepdims=True) * 0.5).astype("float32")

        def __len__(self):
            return 40

    ref = []

    class Rec(paddle.hapi.callbacks.Callback):
        def on_train_batch_end(self, step, logs=None):
            ref.append(float(logs["loss"]))

    model.fit(DS(), batch_size=4, epochs=1, verbose=0, shuffle=False,
              callbacks=[Rec()])
    assert len(ref) == 10
    np.testing.assert_allclose([final[s] for s in range(10)], ref,
                               rtol=2e-4, atol=1e-6)
    print(f"elastic gate leg 1 OK: world {rep['world_history']} "
          f"shrinks={rep['shrinks']} restarts={rep['restarts']} "
          f"rendezvous={rep['rendezvous_rounds']}, resumed from "
          f"step {min(gen1_steps)}, final loss parity to "
          f"{final[9]:.6f}")


def leg_straggler_eviction():
    work = tempfile.mkdtemp(prefix="elastic_gate_straggler_")
    rep, r = _run_leg(
        work, STRAGGLER_TRAINER,
        ["--nproc", "2", "--np", "1:2", "--max_restarts", "1",
         "--evict_stragglers"],
        {"FLAGS_straggler_factor": "2.0",
         "FLAGS_straggler_patience": "2"})

    assert rep["kind"] == "done", rep
    assert rep["restarts"] == 0 and rep["shrinks"] == 1, rep
    assert rep["world"] == 1 and rep["world_history"] == [2, 1], rep
    assert len(rep["stragglers"]) == 1, rep
    s = rep["stragglers"][0]
    assert s["rank"] == "1" and s["generation"] == 0, rep
    # fires at the exact deterministic window: the patience'th strike
    assert s["strikes"] == 2, rep
    assert s["median_s"] > 2.0 * s["gang_median_s"], rep
    assert "evicting straggler rank 1" in r.stderr
    # the re-formed world-1 gang trained to completion
    assert os.path.exists(os.path.join(work, "done_g1_r0"))
    print(f"elastic gate leg 2 OK: straggler rank {s['rank']} evicted "
          f"after {s['strikes']} strikes (median {s['median_s']}s vs "
          f"gang {s['gang_median_s']}s), re-formed at world 1")


def main():
    leg_elastic_relaunch()
    leg_straggler_eviction()
    print("elastic gate OK")


if __name__ == "__main__":
    main()

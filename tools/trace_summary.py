#!/usr/bin/env python
"""trace_summary — chrome-trace JSON -> top-N ops table / request waterfall.

Reads a trace written by ``paddle_tpu.profiler.export_chrome_tracing``
(or any chrome://tracing file with 'X' complete events) and prints the
per-name aggregate the in-process ``Profiler.summary()`` would show:
call count, total/avg/max duration and share of the traced wall time.

    python tools/trace_summary.py trace.json
    python tools/trace_summary.py trace.json -n 20 --sort avg --cat dispatch
    python tools/trace_summary.py trace.json --request <trace-or-request-id>

``--request`` selects the per-request spans recorded by the rtrace
layer (``cat="rtrace"``, matched on ``args.trace_id`` or
``args.request_id``) and renders them as a waterfall: offset from the
request's first span, duration, name, and the outcome/link fields —
the single-request story (ingress -> admission -> queue -> prefill ->
decode... -> egress) that the aggregate table averages away.

Pure stdlib so it runs anywhere the trace file lands (CI artifact
viewers, dev laptops without the framework installed).
"""
import argparse
import json
import sys


def aggregate(events, cat=None):
    """{name: {calls, total_us, avg_us, max_us}} over 'X' events."""
    stats = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        if cat and e.get("cat") != cat:
            continue
        dur = float(e.get("dur", 0.0))
        s = stats.setdefault(e.get("name", "?"),
                             {"calls": 0, "total_us": 0.0, "max_us": 0.0})
        s["calls"] += 1
        s["total_us"] += dur
        if dur > s["max_us"]:
            s["max_us"] = dur
    for s in stats.values():
        s["avg_us"] = s["total_us"] / s["calls"]
    return stats


def format_table(stats, sort="total", top=None):
    key = {"total": "total_us", "avg": "avg_us", "max": "max_us",
           "calls": "calls"}[sort]
    rows = sorted(stats.items(), key=lambda kv: kv[1][key], reverse=True)
    if top:
        rows = rows[:top]
    grand = sum(s["total_us"] for s in stats.values()) or 1.0
    name_w = max([len(n) for n, _ in rows] + [10])
    head = (f"{'name':<{name_w}} {'calls':>7} {'total_ms':>10} "
            f"{'avg_ms':>9} {'max_ms':>9} {'ratio':>6}")
    lines = [head, "-" * len(head)]
    for name, s in rows:
        lines.append(
            f"{name:<{name_w}} {s['calls']:>7} {s['total_us'] / 1e3:>10.3f} "
            f"{s['avg_us'] / 1e3:>9.3f} {s['max_us'] / 1e3:>9.3f} "
            f"{100.0 * s['total_us'] / grand:>5.1f}%")
    if not rows:
        lines.append("(no complete events in trace)")
    return "\n".join(lines)


def request_spans(events, ident):
    """rtrace spans matching ``ident`` (a full trace_id, an
    ``X-Request-Id``, or an unambiguous prefix of either), start-sorted.
    Batch-step spans that *link* the request are folded in too — the
    fused engine work the request shared with its batchmates."""
    spans, batch = [], []
    for e in events:
        if e.get("ph") != "X" or e.get("cat") != "rtrace":
            continue
        a = e.get("args") or {}
        tid, rid = a.get("trace_id", ""), a.get("request_id", "")
        if tid == ident or rid == ident or \
                (len(ident) >= 8 and (tid.startswith(ident)
                                      or rid.startswith(ident))):
            spans.append(e)
        elif a.get("links"):
            batch.append(e)
    # batch spans live on the process trace, so match them through the
    # trace_ids of the directly-matched spans — this way a request-id
    # (or prefix) lookup folds them in just like a trace_id lookup
    roots = {(e.get("args") or {}).get("trace_id") for e in spans}
    for e in batch:
        if any(ln.get("trace_id") in roots
               for ln in (e.get("args") or {}).get("links") or ()):
            spans.append(e)
    spans.sort(key=lambda e: float(e.get("ts", 0.0)))
    return spans


def format_waterfall(spans, ident):
    if not spans:
        return f"(no rtrace spans match {ident!r} — was " \
               "FLAGS_request_trace on when the trace was recorded?)"
    t0 = min(float(e.get("ts", 0.0)) for e in spans)
    meta_keys = ("outcome", "terminated", "status", "slot", "bucket",
                 "occupancy", "members", "rows", "path")
    head = (f"{'offset_ms':>10} {'dur_ms':>9}  span")
    lines = [f"request {ident}", head, "-" * 64]
    for e in spans:
        a = e.get("args") or {}
        extra = " ".join(f"{k}={a[k]}" for k in meta_keys if k in a)
        parent = "" if a.get("parent_id") or not a.get("links") \
            else " [batch]"
        lines.append(
            f"{(float(e.get('ts', 0.0)) - t0) / 1e3:>10.3f} "
            f"{float(e.get('dur', 0.0)) / 1e3:>9.3f}  "
            f"{e.get('name', '?')}{parent}"
            + (f"  ({extra})" if extra else ""))
    ids = {a for a in ((e.get("args") or {}).get("request_id")
                       for e in spans) if a}
    if ids:
        lines.append(f"request ids: {', '.join(sorted(ids))}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="chrome-trace JSON file")
    ap.add_argument("-n", "--top", type=int, default=30,
                    help="show only the top N rows (default 30)")
    ap.add_argument("--sort", choices=("total", "avg", "max", "calls"),
                    default="total")
    ap.add_argument("--cat", default=None,
                    help="restrict to one category (dispatch, collective, "
                         "dataloader, hapi, rtrace, ...)")
    ap.add_argument("--request", default=None, metavar="ID",
                    help="print the span waterfall of one request "
                         "(trace_id / X-Request-Id, or a prefix)")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    if args.request:
        print(format_waterfall(request_spans(events, args.request),
                               args.request))
        return 0
    print(format_table(aggregate(events, cat=args.cat),
                       sort=args.sort, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())

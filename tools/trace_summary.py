#!/usr/bin/env python
"""trace_summary — chrome-trace JSON -> top-N ops table.

Reads a trace written by ``paddle_tpu.profiler.export_chrome_tracing``
(or any chrome://tracing file with 'X' complete events) and prints the
per-name aggregate the in-process ``Profiler.summary()`` would show:
call count, total/avg/max duration and share of the traced wall time.

    python tools/trace_summary.py trace.json
    python tools/trace_summary.py trace.json -n 20 --sort avg --cat dispatch

Pure stdlib so it runs anywhere the trace file lands (CI artifact
viewers, dev laptops without the framework installed).
"""
import argparse
import json
import sys


def aggregate(events, cat=None):
    """{name: {calls, total_us, avg_us, max_us}} over 'X' events."""
    stats = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        if cat and e.get("cat") != cat:
            continue
        dur = float(e.get("dur", 0.0))
        s = stats.setdefault(e.get("name", "?"),
                             {"calls": 0, "total_us": 0.0, "max_us": 0.0})
        s["calls"] += 1
        s["total_us"] += dur
        if dur > s["max_us"]:
            s["max_us"] = dur
    for s in stats.values():
        s["avg_us"] = s["total_us"] / s["calls"]
    return stats


def format_table(stats, sort="total", top=None):
    key = {"total": "total_us", "avg": "avg_us", "max": "max_us",
           "calls": "calls"}[sort]
    rows = sorted(stats.items(), key=lambda kv: kv[1][key], reverse=True)
    if top:
        rows = rows[:top]
    grand = sum(s["total_us"] for s in stats.values()) or 1.0
    name_w = max([len(n) for n, _ in rows] + [10])
    head = (f"{'name':<{name_w}} {'calls':>7} {'total_ms':>10} "
            f"{'avg_ms':>9} {'max_ms':>9} {'ratio':>6}")
    lines = [head, "-" * len(head)]
    for name, s in rows:
        lines.append(
            f"{name:<{name_w}} {s['calls']:>7} {s['total_us'] / 1e3:>10.3f} "
            f"{s['avg_us'] / 1e3:>9.3f} {s['max_us'] / 1e3:>9.3f} "
            f"{100.0 * s['total_us'] / grand:>5.1f}%")
    if not rows:
        lines.append("(no complete events in trace)")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="chrome-trace JSON file")
    ap.add_argument("-n", "--top", type=int, default=30,
                    help="show only the top N rows (default 30)")
    ap.add_argument("--sort", choices=("total", "avg", "max", "calls"),
                    default="total")
    ap.add_argument("--cat", default=None,
                    help="restrict to one category (dispatch, collective, "
                         "dataloader, hapi, ...)")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    print(format_table(aggregate(events, cat=args.cat),
                       sort=args.sort, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())

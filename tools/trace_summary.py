#!/usr/bin/env python
"""trace_summary — chrome-trace JSON -> top-N ops table / request waterfall.

Reads a trace written by ``paddle_tpu.profiler.export_chrome_tracing``
(or any chrome://tracing file with 'X' complete events) and prints the
per-name aggregate the in-process ``Profiler.summary()`` would show:
call count, total/avg/max duration and share of the traced wall time.

    python tools/trace_summary.py trace.json
    python tools/trace_summary.py trace.json -n 20 --sort avg --cat dispatch
    python tools/trace_summary.py trace.json --request <trace-or-request-id>
    python tools/trace_summary.py trace.json --compiles
    python tools/trace_summary.py trace.json --request <id> \\
        --flight /tmp/flight/flight.r0.g0.json

``--request`` selects the per-request spans recorded by the rtrace
layer (``cat="rtrace"``, matched on ``args.trace_id`` or
``args.request_id``) and renders them as a waterfall: offset from the
request's first span, duration, name, and the outcome/link fields —
the single-request story (ingress -> admission -> queue -> prefill ->
decode... -> egress) that the aggregate table averages away.
``--flight`` (repeatable) folds flight-recorder dump events stamped
with the same request id into that waterfall, so the operational
verdicts (kv_shed, exhaustion, retire reason) line up with the spans.

``--compiles`` prints the memscope compile-ledger view off the
``cat="compile"`` spans: per site x cause x provenance, how many
compiles and how much wall they burned.

``--memplan`` treats the positional argument as a static memory plan
JSON (``MemoryPlan.to_doc()`` from static/passes/memory_plan.py, e.g.
dumped by tools/memplan_gate.py) instead of a chrome trace, and renders
the per-op live-byte timeline with tag columns and the peak marker:

    python tools/trace_summary.py plan.json --memplan -n 20

Pure stdlib so it runs anywhere the trace file lands (CI artifact
viewers, dev laptops without the framework installed).
"""
import argparse
import json
import sys


def aggregate(events, cat=None):
    """{name: {calls, total_us, avg_us, max_us}} over 'X' events."""
    stats = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        if cat and e.get("cat") != cat:
            continue
        dur = float(e.get("dur", 0.0))
        s = stats.setdefault(e.get("name", "?"),
                             {"calls": 0, "total_us": 0.0, "max_us": 0.0})
        s["calls"] += 1
        s["total_us"] += dur
        if dur > s["max_us"]:
            s["max_us"] = dur
    for s in stats.values():
        s["avg_us"] = s["total_us"] / s["calls"]
    return stats


def format_table(stats, sort="total", top=None):
    key = {"total": "total_us", "avg": "avg_us", "max": "max_us",
           "calls": "calls"}[sort]
    rows = sorted(stats.items(), key=lambda kv: kv[1][key], reverse=True)
    if top:
        rows = rows[:top]
    grand = sum(s["total_us"] for s in stats.values()) or 1.0
    name_w = max([len(n) for n, _ in rows] + [10])
    head = (f"{'name':<{name_w}} {'calls':>7} {'total_ms':>10} "
            f"{'avg_ms':>9} {'max_ms':>9} {'ratio':>6}")
    lines = [head, "-" * len(head)]
    for name, s in rows:
        lines.append(
            f"{name:<{name_w}} {s['calls']:>7} {s['total_us'] / 1e3:>10.3f} "
            f"{s['avg_us'] / 1e3:>9.3f} {s['max_us'] / 1e3:>9.3f} "
            f"{100.0 * s['total_us'] / grand:>5.1f}%")
    if not rows:
        lines.append("(no complete events in trace)")
    return "\n".join(lines)


def request_spans(events, ident):
    """rtrace spans matching ``ident`` (a full trace_id, an
    ``X-Request-Id``, or an unambiguous prefix of either), start-sorted.
    Batch-step spans that *link* the request are folded in too — the
    fused engine work the request shared with its batchmates."""
    spans, batch = [], []
    for e in events:
        if e.get("ph") != "X" or e.get("cat") != "rtrace":
            continue
        a = e.get("args") or {}
        tid, rid = a.get("trace_id", ""), a.get("request_id", "")
        if tid == ident or rid == ident or \
                (len(ident) >= 8 and (tid.startswith(ident)
                                      or rid.startswith(ident))):
            spans.append(e)
        elif a.get("links"):
            batch.append(e)
    # batch spans live on the process trace, so match them through the
    # trace_ids of the directly-matched spans — this way a request-id
    # (or prefix) lookup folds them in just like a trace_id lookup
    roots = {(e.get("args") or {}).get("trace_id") for e in spans}
    for e in batch:
        if any(ln.get("trace_id") in roots
               for ln in (e.get("args") or {}).get("links") or ()):
            spans.append(e)
    spans.sort(key=lambda e: float(e.get("ts", 0.0)))
    return spans


def format_waterfall(spans, ident):
    if not spans:
        return f"(no rtrace spans match {ident!r} — was " \
               "FLAGS_request_trace on when the trace was recorded?)"
    t0 = min(float(e.get("ts", 0.0)) for e in spans)
    meta_keys = ("outcome", "terminated", "status", "slot", "bucket",
                 "occupancy", "members", "rows", "path")
    head = (f"{'offset_ms':>10} {'dur_ms':>9}  span")
    lines = [f"request {ident}", head, "-" * 64]
    for e in spans:
        a = e.get("args") or {}
        extra = " ".join(f"{k}={a[k]}" for k in meta_keys if k in a)
        parent = "" if a.get("parent_id") or not a.get("links") \
            else " [batch]"
        lines.append(
            f"{(float(e.get('ts', 0.0)) - t0) / 1e3:>10.3f} "
            f"{float(e.get('dur', 0.0)) / 1e3:>9.3f}  "
            f"{e.get('name', '?')}{parent}"
            + (f"  ({extra})" if extra else ""))
    ids = {a for a in ((e.get("args") or {}).get("request_id")
                       for e in spans) if a}
    if ids:
        lines.append(f"request ids: {', '.join(sorted(ids))}")
    return "\n".join(lines)


def compile_table(events):
    """Per (site, cause, provenance) compile accounting off the
    ``cat="compile"`` spans memscope mirrors into the trace ring."""
    rows = {}
    for e in events:
        if e.get("ph") != "X" or e.get("cat") != "compile":
            continue
        a = e.get("args") or {}
        site = e.get("name", "?").replace("compile::", "", 1)
        k = (site, a.get("cause", "?"), a.get("provenance", "?"))
        r = rows.setdefault(k, {"count": 0, "total_us": 0.0})
        r["count"] += 1
        r["total_us"] += float(e.get("dur", 0.0))
    if not rows:
        return "(no compile spans in trace — was FLAGS_mem_accounting " \
               "on with the tracer live?)"
    site_w = max([len(k[0]) for k in rows] + [8])
    head = (f"{'site':<{site_w}} {'cause':<12} {'provenance':<11} "
            f"{'count':>6} {'total_ms':>10}")
    lines = [head, "-" * len(head)]
    for (site, cause, prov), r in sorted(
            rows.items(), key=lambda kv: kv[1]["total_us"],
            reverse=True):
        lines.append(f"{site:<{site_w}} {cause:<12} {prov:<11} "
                     f"{r['count']:>6} {r['total_us'] / 1e3:>10.3f}")
    return "\n".join(lines)


def flight_events_for(paths, ident):
    """Events from flight-recorder dump files whose ``request_id``
    field matches ``ident`` (or a >=8-char prefix), as synthetic
    zero-duration rtrace spans the waterfall can interleave.  Flight
    timestamps are unix seconds; rtrace spans are perf_counter_ns —
    different clocks — so folded events sort by their own time among
    themselves and render with an ``[flight]`` marker instead of an
    offset."""
    out = []
    for path in paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"trace_summary: skipping flight dump {path}: {e}",
                  file=sys.stderr)
            continue
        for ev in doc.get("events") or []:
            fields = ev.get("fields") or {}
            rid = str(fields.get("request_id")
                      or fields.get("request") or "")
            if not rid:
                continue
            if rid == ident or (len(ident) >= 8
                                and rid.startswith(ident)):
                out.append({"t": float(ev.get("t", 0.0)),
                            "name": f"{ev.get('cat')}.{ev.get('event')}",
                            "fields": {k: v for k, v in fields.items()
                                       if k not in ("request_id",
                                                    "request")},
                            "source": path})
    out.sort(key=lambda e: e["t"])
    return out


def format_flight_tail(flight_evs):
    if not flight_evs:
        return ""
    lines = ["", "flight events (same request, flight-recorder clock):"]
    for ev in flight_evs:
        extra = " ".join(f"{k}={v}" for k, v in ev["fields"].items())
        lines.append(f"  [flight] {ev['t']:.3f} {ev['name']}"
                     + (f"  ({extra})" if extra else ""))
    return "\n".join(lines)


def format_memplan(doc, top=None):
    """Render a ``MemoryPlan.to_doc()`` JSON: header with the peak, then
    the per-op timeline (optionally only the top-N rows by live bytes,
    kept in program order) with per-tag byte columns."""
    if doc.get("kind") != "memory_plan":
        return "(not a memory plan: expected a JSON object with " \
               "kind='memory_plan' — is this a MemoryPlan.to_doc() dump?)"
    mb = 1024.0 * 1024.0
    peak_op = doc.get("peak_op") or {}
    lines = [
        f"memory plan: peak {doc.get('peak_bytes', 0) / mb:.3f} MB at "
        f"op#{peak_op.get('idx', '?')} '{peak_op.get('type', '?')}' "
        f"({doc.get('live_ops', '?')} live / {doc.get('n_ops', '?')} ops, "
        f"static {doc.get('static_bytes', 0) / mb:.3f} MB)"]
    by_tag = doc.get("static_by_tag") or {}
    if by_tag:
        lines.append("static: " + "  ".join(
            f"{k}={v / mb:.3f}MB" for k, v in sorted(by_tag.items()) if v))
    head = (f"{'op':>4} {'type':<24} {'kind':<8} {'live_mb':>9} "
            f"{'params':>8} {'acts':>8} {'grads':>8} {'opt':>8}")
    lines += [head, "-" * len(head)]
    rows = doc.get("timeline") or []
    if top and len(rows) > top:
        keep = {r["idx"] for r in sorted(
            rows, key=lambda r: r.get("live_bytes", 0), reverse=True)[:top]}
        rows = [r for r in rows if r["idx"] in keep]
    peak_idx = peak_op.get("idx")
    for r in rows:
        t = r.get("by_tag") or {}
        mark = "  <- peak" if r.get("idx") == peak_idx else ""
        lines.append(
            f"{r.get('idx', '?'):>4} {r.get('type', '?'):<24.24} "
            f"{r.get('kind', '?'):<8} "
            f"{r.get('live_bytes', 0) / mb:>9.3f} "
            f"{t.get('params', 0) / mb:>8.3f} "
            f"{t.get('activations', 0) / mb:>8.3f} "
            f"{t.get('grads', 0) / mb:>8.3f} "
            f"{t.get('opt_state', 0) / mb:>8.3f}{mark}")
    if not rows:
        lines.append("(empty timeline)")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace", help="chrome-trace JSON file")
    ap.add_argument("-n", "--top", type=int, default=30,
                    help="show only the top N rows (default 30)")
    ap.add_argument("--sort", choices=("total", "avg", "max", "calls"),
                    default="total")
    ap.add_argument("--cat", default=None,
                    help="restrict to one category (dispatch, collective, "
                         "dataloader, hapi, rtrace, ...)")
    ap.add_argument("--request", default=None, metavar="ID",
                    help="print the span waterfall of one request "
                         "(trace_id / X-Request-Id, or a prefix)")
    ap.add_argument("--flight", action="append", default=[],
                    metavar="PATH",
                    help="flight-recorder dump(s) to fold into the "
                         "--request waterfall (repeatable)")
    ap.add_argument("--compiles", action="store_true",
                    help="print the compile-ledger table "
                         "(cat='compile' spans: site/cause/provenance)")
    ap.add_argument("--memplan", action="store_true",
                    help="treat the positional arg as a static memory "
                         "plan JSON (MemoryPlan.to_doc()) and render "
                         "its per-op live-byte timeline")
    args = ap.parse_args(argv)
    with open(args.trace) as f:
        doc = json.load(f)
    if args.memplan:
        print(format_memplan(doc, top=args.top))
        return 0
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    if args.compiles:
        print(compile_table(events))
        return 0
    if args.request:
        print(format_waterfall(request_spans(events, args.request),
                               args.request))
        tail = format_flight_tail(
            flight_events_for(args.flight, args.request))
        if tail:
            print(tail)
        return 0
    print(format_table(aggregate(events, cat=args.cat),
                       sort=args.sort, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())

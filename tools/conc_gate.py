#!/usr/bin/env python
"""CI concurrency-sanitizer gate: the serving, decode, and pipeline
soaks re-run with the runtime lock sanitizer armed.

Each gate subprocess runs with ``FLAGS_lock_san=1`` and
``PADDLE_LOCK_SAN_REPORT`` pointed at a scratch file; the sanitizer
writes its process summary at exit.  The gate then asserts, per
subprocess:

- the gate's own checks passed (bit-exactness, chaos counts, compile
  bounds — the sanitizer must not change behavior);
- the sanitizer actually engaged (instrumented acquires > 0 — a
  silently-plain-lock run would vacuously "pass");
- **zero lock-order cycles** were recorded across the whole run — the
  engines' locks, the executable cache, admission, the generation
  trace lock, the checkpoint writer, and the profiler internals all
  acquired in a globally consistent order under real concurrency;
- **zero holds over threshold**: no critical section exceeded
  ``FLAGS_lock_hold_warn_ms`` (set here to {HOLD_MS:.0f} ms — wide
  enough for legitimate cold-start XLA compiles under the baselined
  trace lock on a loaded CI box, tight enough that a wedged worker
  parked on a lock trips it).

Wired into tools/run_all_tests.sh after the decode gate.
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# per-gate engagement floors: "acquires > 0" alone would let a leg
# pass with the sanitizer essentially idle (the pipeline soak's fit
# path is deliberately lock-light at num_workers=0 — the loader soak
# below covers those locks instead)
GATES = [("serving_gate.py", 100), ("decode_gate.py", 100),
         ("pipeline_gate.py", 1)]
HOLD_MS = 15000.0

__doc__ = __doc__.replace("{HOLD_MS:.0f}", f"{HOLD_MS:.0f}")

# Training-pipeline lock soak: pipeline_gate's fit contract runs at
# num_workers=0 (indexed-mode bit-exactness), which never touches the
# sanitized io.loader cursor/results locks.  This leg drives them
# directly — thread workers forced, several concurrent loaders plus
# async checkpoint saves — so the io/ckpt path's ordering is ACTUALLY
# exercised under the sanitizer, not vacuously green.
LOADER_SOAK = r"""
import numpy as np, threading
import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, TensorDataset
from paddle_tpu.distributed import checkpoint as ckpt
import tempfile, os
xs = paddle.to_tensor(np.arange(256, dtype=np.float32).reshape(64, 4))
results, errors = [], []
def one_epoch(seed):
    try:
        ds = TensorDataset([xs])
        dl = DataLoader(ds, batch_size=4, shuffle=True, num_workers=2,
                        use_shared_memory=False)
        results.append([np.asarray(b[0].numpy()).sum() for b in dl])
    except BaseException as e:   # a dead epoch must FAIL the leg
        errors.append(repr(e))
ts = [threading.Thread(target=one_epoch, args=(i,)) for i in range(3)]
[t.start() for t in ts]; [t.join() for t in ts]
assert not errors, errors
assert len(results) == 3 and all(len(r) == 16 for r in results), \
    [len(r) for r in results]
d = tempfile.mkdtemp(prefix="conc_soak_")
for step in range(3):
    ckpt.save_state(os.path.join(d, f"s{step}"),
                    {"w": paddle.to_tensor(np.ones(4, np.float32))},
                    use_async=True, step=step)
ckpt.wait_all()
print("loader soak done")
"""


def run_gate(script, report_dir: str, floor: int, argv=None,
             extra_env=None) -> dict:
    label = script if isinstance(script, str) else "loader_soak"
    report = os.path.join(report_dir,
                          label.replace(".py", "") + ".san.json")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": env.get("JAX_PLATFORMS", "cpu"),
        "FLAGS_lock_san": "1",
        "FLAGS_lock_hold_warn_ms": str(HOLD_MS),
        "PADDLE_LOCK_SAN_REPORT": report,
        # sanitizer RuntimeWarnings must not abort a gate subprocess
        # under a CI-wide PYTHONWARNINGS=error; they are accounted in
        # the report this gate asserts on instead
        "PYTHONWARNINGS": "default",
    })
    env.update(extra_env or {})
    cmd = argv if argv is not None else \
        [sys.executable, os.path.join(REPO, "tools", script)]
    rc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                        timeout=1800)
    if rc.returncode != 0:
        raise AssertionError(
            f"{label} FAILED under FLAGS_lock_san=1 "
            f"(rc={rc.returncode}):\n--- stdout ---\n{rc.stdout[-4000:]}"
            f"\n--- stderr ---\n{rc.stderr[-4000:]}")
    if not os.path.exists(report):
        raise AssertionError(
            f"{label}: sanitizer report {report} was never written — "
            "FLAGS_lock_san did not reach the subprocess?")
    with open(report) as f:
        rep = json.load(f)
    assert rep["acquires"] >= floor, (
        f"{label}: only {rep['acquires']} instrumented acquires "
        f"(floor {floor}) — the sanitizer barely engaged; the run "
        "proves little")
    assert rep["cycles"] == 0, (
        f"{label}: {rep['cycles']} lock-order cycle(s) recorded: "
        f"{rep['cycle_reports']}")
    assert rep["long_holds"] == 0, (
        f"{label}: {rep['long_holds']} lock hold(s) over "
        f"{HOLD_MS:.0f}ms — a critical section is wedging its waiters")
    return rep


def main():
    report_dir = tempfile.mkdtemp(prefix="conc_gate_")
    total_acq = 0
    legs = [(s, f, None, None) for s, f in GATES]
    legs.append(("loader_soak", 50, [sys.executable, "-c", LOADER_SOAK],
                 {"PADDLE_TPU_THREAD_WORKERS": "1"}))
    for script, floor, argv, extra_env in legs:
        rep = run_gate(script, report_dir, floor, argv, extra_env)
        total_acq += rep["acquires"]
        edges = sum(len(d) for d in rep.get("edges", {}).values())
        label = script if isinstance(script, str) else "loader_soak"
        print(f"conc gate: {label} OK under lock-san — "
              f"{rep['acquires']} acquires ({rep['contended']} "
              f"contended), {edges} order edges, 0 cycles, "
              f"0 long holds")
    print(f"conc gate OK: serving+decode+pipeline+loader soaks clean "
          f"under FLAGS_lock_san=1 ({total_acq} instrumented "
          f"acquires, zero lock-order cycles, zero holds > "
          f"{HOLD_MS:.0f}ms)")


if __name__ == "__main__":
    main()

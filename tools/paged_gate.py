#!/usr/bin/env python
"""CI paged gate: the PagedGenerationEngine (block-pool KV memory,
PR 11) under staggered concurrent streams with a FIXED
``kv.block_alloc`` chaos spec must lose nothing, stream bit-exact
sequences against the PR 6 contiguous references, shed EXACTLY the
injected count with the typed reason, hit the prefix cache a pinned
number of times on a repeated-system-prompt workload, and compile no
more executables than the bucket bound allows.

Four phases:

1. chaos soak — 3 client threads x 4 staggered generation requests
   (mixed prompt lengths, mixed greedy/sampled configs, per-request
   seeds) under ``kv.block_alloc:fail@7`` (the 7th block allocation,
   globally, is injected to exhaust): every request must either stream
   to completion or be THE single typed
   ``RequestRejected(reason="kv_blocks")`` shed; zero lost; the pool
   drains to all-free after close (no leaked refcounts).
2. parity — every completed stream (iterator tokens AND final result)
   must be IDENTICAL to a sequential CONTIGUOUS
   ``GenerationSession.generate`` reference: paging, block tables,
   lazy growth, admission timing, and the shed neighbour may not
   change a single token.
3. prefix cache — N requests sharing one system-prompt prefix with
   distinct tails: exactly N-1 hits, >= (N-1) * block_size prompt
   tokens served from cache, and the hitting streams still equal their
   cold references.
4. accounting — total XLA compiles <= one chunk executable per pow2
   prompt bucket + one width-1 decode + one block-copy helper (block
   tables are DATA: they never enter a compile key); shed counter ==
   injected counter == 1.

Wired into tools/run_all_tests.sh next to the decode gate.
"""
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

CHAOS_SPEC = "kv.block_alloc:fail@7"
CLIENTS, PER_CLIENT = 3, 4
MAX_NEW = 5
BS = 16


def val(name):
    from paddle_tpu.profiler import metrics
    m = metrics.get(name)
    return m.value if m is not None else 0


def main():
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.generation import GenerationSession
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.serving.bucketing import seq_buckets

    paddle.seed(0)
    net = GPT(GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64, ffn_mult=2))
    engine = serving.PagedGenerationEngine(
        net, serving.GenerationEngineConfig(
            max_slots=4, max_length=64, max_new_tokens=MAX_NEW,
            block_size=BS, name="pg_soak"))

    rng = np.random.RandomState(7)
    jobs = []
    for c in range(CLIENTS):
        for r in range(PER_CLIENT):
            n = int(rng.randint(3, 11))
            jobs.append(dict(
                prompt=rng.randint(1, 97, (n,)).astype(np.int32),
                kw=dict(max_new_tokens=MAX_NEW,
                        do_sample=bool((c + r) % 2),
                        temperature=0.8, top_k=12, top_p=0.95,
                        seed=1000 + 10 * c + r)))

    # -- phase 1: kv.block_alloc chaos soak ---------------------------
    paddle.set_flags({"FLAGS_chaos_spec": CHAOS_SPEC})
    ok, shed, lost = [], [], []

    def client(tid):
        for r in range(PER_CLIENT):
            time.sleep(0.002 * (tid + r))     # staggered arrivals
            job = jobs[tid * PER_CLIENT + r]
            try:
                stream = engine.submit(job["prompt"], **job["kw"])
                toks = list(stream)           # the STREAMED sequence
                final = stream.result(timeout=300)
            except serving.RequestRejected as e:
                if e.reason == "kv_blocks":   # the typed injected shed
                    shed.append((tid, r))
                else:
                    lost.append(f"untyped rejection ({tid},{r}): {e}")
                continue
            except Exception as e:            # anything else is lost
                lost.append(repr(e))
                continue
            if toks != final.tolist():
                lost.append(f"stream/result mismatch ({tid},{r})")
            else:
                job["got"] = final
                ok.append((tid, r))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    paddle.set_flags({"FLAGS_chaos_spec": ""})

    total = CLIENTS * PER_CLIENT
    assert not lost, f"lost/wrong requests: {lost}"
    assert len(shed) == 1, \
        f"expected exactly 1 kv_blocks shed, got {len(shed)}"
    assert len(ok) == total - 1, (len(ok), total)
    assert val("chaos.injected.kv.block_alloc") == 1
    assert val("pg_soak.request.shed_kv_blocks") == 1
    assert val("pg_soak.kv.alloc_exhausted") == 1
    engine.close()
    assert engine.pool.available == engine.pool.num_blocks, \
        "leaked KV blocks after soak + close"

    # -- phase 2: paged streams == contiguous references --------------
    ref_ses = GenerationSession(net, batch_capacity=4, max_length=64,
                                name="pg_ref")
    for job in jobs:
        if "got" not in job:
            continue
        ref = ref_ses.generate([job["prompt"]], **job["kw"])[0]
        assert np.array_equal(job["got"], ref), \
            (job["got"], ref, "paging changed tokens")

    # -- phase 3: pinned prefix-cache hits ----------------------------
    sys_prompt = np.tile(np.int32([11, 12, 13, 14, 15]), 5)  # 25 toks
    N = 4
    peng = serving.PagedGenerationEngine(
        net, serving.GenerationEngineConfig(
            max_slots=4, max_length=64, max_new_tokens=MAX_NEW,
            block_size=BS, prefix_cache_blocks=8, name="pg_prefix"))
    outs = []
    for i in range(N):
        tail = np.int32([40 + i, 50 + i])
        outs.append(peng.generate(
            np.concatenate([sys_prompt, tail]),
            max_new_tokens=MAX_NEW, timeout=300))
    hits = val("pg_prefix.prefix_cache.hit")
    hit_tokens = val("pg_prefix.prefix_cache.hit_tokens")
    assert hits == N - 1, \
        f"expected exactly {N - 1} prefix-cache hits, got {hits}"
    assert hit_tokens >= (N - 1) * BS, (hit_tokens, (N - 1) * BS)
    for i, got in enumerate(outs):        # hitters equal cold refs
        tail = np.int32([40 + i, 50 + i])
        ref = ref_ses.generate(
            [np.concatenate([sys_prompt, tail])],
            max_new_tokens=MAX_NEW)[0]
        assert np.array_equal(got, ref), \
            "prefix-cache hit changed tokens"
    peng.close()
    assert peng.pool.available == peng.pool.num_blocks, \
        "leaked KV blocks after prefix workload + close"

    # -- phase 4: compile accounting ----------------------------------
    # per engine: one chunk executable per pow2 suffix bucket + the
    # width-1 decode + one block-copy (COW) helper; block tables and
    # pool state are data, never key material
    bound = len(seq_buckets(64, engine.config.prompt_bucket_min)) + 2
    for name in ("pg_soak", "pg_prefix"):
        compiles = val(f"{name}.compile")
        assert 0 < compiles <= bound, \
            f"{name}: {compiles} compiles (bound {bound})"
    assert val("pg_soak.request.completed") == len(ok)
    assert val("pg_prefix.request.completed") == N
    print(f"paged gate OK: {len(ok)}/{total} streamed bit-exact vs "
          f"contiguous refs, 1 typed kv_blocks shed (injected), "
          f"{val('pg_prefix.prefix_cache.hit')} pinned prefix hits "
          f"({hit_tokens} tokens served from cache), compiles "
          f"{val('pg_soak.compile')}/{val('pg_prefix.compile')} "
          f"(bound {bound}), pools drained to all-free")


if __name__ == "__main__":
    main()

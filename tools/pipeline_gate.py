#!/usr/bin/env python
"""CI async-pipeline gate: the device-prefetched ``Model.fit`` must be
**bit-exact** vs the unprefetched loop on a fixed-seed 20-step run, with
the prefetch queue observed non-empty in steady state and ZERO lost
batches when a ``loader.worker`` chaos kill takes out a fetch mid-epoch.

Three runs of the same fixed-seed model/data (shuffle off, 20 steps):

1. synchronous  — ``prefetch_to_device=0`` (reference trajectory)
2. prefetched   — ``DataLoader(prefetch_to_device=2)`` (the Model.fit
   default path), asserted bit-identical per-step losses AND a queue
   that actually ran ahead (nonempty_gets > 0, produced == steps)
3. chaos        — same as 2 under ``loader.worker:fail@7``: the
   prefetch stage must refetch the killed batch (refetch == injected
   == 1) and still deliver every batch bit-exactly

Also asserts the sync-free contract the lazy-loss pipeline documents:
the steady-state loop materializes the loss on the host at most once
per ``log_freq`` window (train.loss_fetch counter).

Wired into tools/run_all_tests.sh.
"""
import os
import sys
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu.hapi.callbacks import Callback  # noqa: E402
from paddle_tpu.profiler import metrics  # noqa: E402
from paddle_tpu.utils import chaos  # noqa: E402

STEPS = 20
BATCH = 4


class DS(paddle.io.Dataset):
    def __getitem__(self, i):
        rng = np.random.RandomState(i)
        x = rng.rand(8).astype("float32")
        return x, (x.sum(keepdims=True) * 0.25).astype("float32")

    def __len__(self):
        return STEPS * BATCH


class CaptureLoss(Callback):
    def __init__(self):
        super().__init__()
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        # keep the LAZY scalar; materialize after fit so the capture
        # itself doesn't add per-step syncs
        self.losses.append(logs["loss"])


def run(prefetch_depth, log_freq=5):
    paddle.seed(1234)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
                               paddle.nn.Linear(16, 1))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
    model.prepare(opt, paddle.nn.MSELoss())
    loader = paddle.io.DataLoader(DS(), batch_size=BATCH, shuffle=False,
                                  prefetch_to_device=prefetch_depth)
    cap = CaptureLoss()
    fetch0 = metrics.counter("train.loss_fetch").value
    # prefetch_to_device=0 must go to fit as well: otherwise the
    # FLAGS_prefetch_to_device default wraps the loader at the fit
    # level and the "synchronous" reference leg silently prefetches
    model.fit(loader, epochs=1, verbose=2, log_freq=log_freq,
              callbacks=[cap], prefetch_to_device=prefetch_depth)
    fetches_in_fit = metrics.counter("train.loss_fetch").value - fetch0
    losses = np.asarray([float(l) for l in cap.losses], np.float64)
    return losses, loader._last_prefetcher, fetches_in_fit


def main():
    fails = []

    def check(ok, msg):
        print(("PASS " if ok else "FAIL ") + msg)
        if not ok:
            fails.append(msg)

    # 1) reference: synchronous loop
    ref, pf, ref_fetches = run(0)
    check(pf is None and len(ref) == STEPS,
          f"synchronous run: {len(ref)} steps, no prefetch stage")
    # verbose=2/log_freq=5 prints at steps 0,5,10,15 + epoch end: the
    # loop may block at most once per window (+1 for the epoch line)
    budget = STEPS // 5 + 2
    check(0 < ref_fetches <= budget,
          f"sync-free contract: {ref_fetches} loss fetches in fit for "
          f"{STEPS} steps @ log_freq=5 (budget {budget})")

    # 2) prefetched, bit-exact + queue ran ahead
    got, pf, pf_fetches = run(2)
    check(pf is not None and np.array_equal(ref, got),
          "prefetched fit bit-exact vs synchronous loop "
          f"(max |d|={np.max(np.abs(ref - got)) if len(ref) == len(got) else 'len mismatch'})")
    check(pf is not None and pf.stats["produced"] == STEPS
          and pf.stats["gets"] == STEPS,
          f"zero lost batches: produced={pf.stats['produced']} "
          f"consumed={pf.stats['gets']} of {STEPS}")
    check(pf is not None and pf.stats["nonempty_gets"] > 0,
          f"prefetch queue non-empty in steady state "
          f"(nonempty_gets={pf.stats['nonempty_gets']}/{STEPS}, "
          f"max_depth={pf.stats['max_depth']})")
    check(0 < pf_fetches <= budget,
          f"sync-free contract (prefetched): {pf_fetches} loss fetches "
          f"(budget {budget})")

    # 3) loader.worker chaos kill: recovered, nothing lost, bit-exact
    refetch0 = metrics.counter("io.prefetch.refetch").value
    chaos.configure("loader.worker:fail@7", seed=0)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            got_c, pf_c, _ = run(2)
    finally:
        chaos.reset()
    injected = metrics.counter("chaos.injected.loader.worker").value
    refetches = metrics.counter("io.prefetch.refetch").value - refetch0
    check(injected == 1, f"chaos injected exactly one worker kill "
          f"(got {injected})")
    check(pf_c is not None and refetches == 1
          and pf_c.stats["refetch"] == 1,
          f"killed fetch refetched in place (refetch={refetches})")
    check(pf_c is not None and pf_c.stats["produced"] == STEPS
          and len(got_c) == STEPS,
          f"zero lost batches under chaos kill (produced="
          f"{pf_c.stats['produced'] if pf_c else None}, "
          f"steps={len(got_c)})")
    check(np.array_equal(ref, got_c),
          "chaos run still bit-exact vs synchronous loop")

    if fails:
        print(f"pipeline gate: {len(fails)} check(s) failed",
              file=sys.stderr)
        return 1
    print("pipeline gate: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())

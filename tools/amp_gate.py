#!/usr/bin/env python
"""CI gate for the AMP train step — the ISSUE-20 bf16/fp16 layer, end
to end in fresh subprocesses.

Leg 1 (parity): the same seeded MLP trains 10 steps in fp32, O1-bf16
and O2-bf16 through ``Model.fit``'s jitted step.  Per-step losses must
track the fp32 trajectory within the documented bf16 tolerance, the
bf16 legs must never engage the loss scaler, and a warm second run of
10 steps under memscope must add ZERO compile-ledger entries (the
scaler/rng threading must not grow the jit signature).  The measured
steady-step bf16/fp32 ratio is reported (not asserted: CPU emulates
bf16 in software; the TPU run is the perf measurement).

Leg 2 (fp16 found-inf): a float16 O1 run with dynamic loss scaling —
a clean step updates params and keeps the scale; an inf-poisoned batch
must set found_inf, leave params and opt state bit-unchanged, and
halve the scale (decr_every_n_nan_or_inf=1); the next clean batch
must resume updating with a finite loss.

Leg 3 (lint): a train forward captured through dy2static under
``auto_cast`` must lint ZERO AMP findings and emit a cast plan whose
white list covers the matmul class; a deliberately narrowed program
(bf16 fed straight into a black-list op) must trip AMP01 — the lint
both passes clean programs and catches genuine narrowing.

Wired into tools/run_all_tests.sh.
"""
import os
import subprocess
import sys
import tempfile
import textwrap

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

PARITY = """
import time
import warnings
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.profiler import memscope

STEPS = 10
rng = np.random.RandomState(0)
xs = [rng.rand(16, 32).astype("float32") for _ in range(STEPS)]
ys = [rng.randint(0, 8, (16,)).astype("int64") for _ in range(STEPS)]


def run(amp_configs):
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.LayerNorm(64),
                        nn.Linear(64, 8))
    m = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    m.prepare(opt, nn.CrossEntropyLoss(), amp_configs=amp_configs)
    losses, times = [], []
    for i in range(STEPS):
        t0 = time.perf_counter()
        logs = m.train_batch([xs[i]], [ys[i]])
        losses.append(float(logs["loss"]))
        times.append(time.perf_counter() - t0)
    # steady-step time: drop the compile-bearing first steps
    steady = float(np.mean(times[STEPS // 2:]))
    return m, losses, steady


ref_m, ref, t_fp32 = run(None)
docs = {"fp32_loss": [round(v, 4) for v in ref]}
for name, cfg in (("O1", {"level": "O1", "dtype": "bfloat16"}),
                  ("O2", {"level": "O2", "dtype": "bfloat16"})):
    m, got, t_bf16 = run(cfg)
    for i, (a, b) in enumerate(zip(ref, got)):
        assert np.isfinite(b), f"{name}: non-finite loss at step {i}"
        # bf16 keeps an 8-bit mantissa: per-step losses track fp32
        # within a few parts per hundred on this shallow net
        assert abs(a - b) <= 5e-2 * max(1.0, abs(a)), (
            f"{name}: step {i} loss {b} vs fp32 {a} outside the "
            "documented bf16 tolerance")
    assert m._amp_scaler_state is None, (
        f"{name}: bf16 must never engage the loss scaler")
    docs[name] = {"loss": [round(v, 4) for v in got],
                  "steady_ratio_vs_fp32": round(t_bf16 / t_fp32, 3)}

    # warm rerun: same shapes through the SAME Model — the compile
    # ledger must not grow (scaler/rng state threading is signature-
    # stable across steps)
    memscope.enable()
    c0 = memscope.compile_count()
    for i in range(STEPS):
        m.train_batch([xs[i]], [ys[i]])
    c1 = memscope.compile_count()
    memscope.disable()
    assert c1 == c0, (
        f"{name}: warm steps added {c1 - c0} compile(s) — the AMP "
        "step signature is unstable")

print("parity leg ok:", docs)
"""

FP16 = """
import warnings
import numpy as np
import jax
import paddle_tpu as paddle
import paddle_tpu.nn as nn

rng = np.random.RandomState(0)
paddle.seed(0)
net = nn.Sequential(nn.Linear(32, 64), nn.ReLU(), nn.Linear(64, 8))
m = paddle.Model(net)
opt = paddle.optimizer.Adam(learning_rate=1e-3,
                            parameters=net.parameters())
m.prepare(opt, nn.CrossEntropyLoss(),
          amp_configs={"level": "O1", "dtype": "float16",
                       "init_loss_scaling": 1024.0,
                       "decr_every_n_nan_or_inf": 1})

x = rng.rand(16, 32).astype("float32")
y = rng.randint(0, 8, (16,)).astype("int64")

# clean step: params move, scale holds, no inf found
logs = m.train_batch([x], [y])
assert np.isfinite(logs["loss"])
assert not bool(m._amp_found_inf), "clean batch reported found_inf"
assert float(m._amp_scaler_state["scale"]) == 1024.0

snap = {n: np.asarray(p) for n, p in net.functional_state()[0].items()}
bad = x.copy()
bad[0, 0] = np.inf

# poisoned step: found_inf latches, the update is SKIPPED bit-exactly
# and the dynamic scale halves (decr_every_n_nan_or_inf=1)
m.train_batch([bad], [y])
assert bool(m._amp_found_inf), "inf batch did not set found_inf"
after = {n: np.asarray(p) for n, p in net.functional_state()[0].items()}
for n in snap:
    assert (snap[n] == after[n]).all(), (
        f"param {n} changed on a found_inf step")
assert float(m._amp_scaler_state["scale"]) == 512.0, (
    f"scale {float(m._amp_scaler_state['scale'])} != 512 after one "
    "nan/inf step")

# recovery: the next clean batch updates params with a finite loss
logs = m.train_batch([x], [y])
assert np.isfinite(logs["loss"])
assert not bool(m._amp_found_inf)
after2 = {n: np.asarray(p) for n, p in net.functional_state()[0].items()}
assert any((after2[n] != snap[n]).any() for n in snap), (
    "clean batch after skip did not update params")
print("fp16 leg ok:", {"scale_after_skip": 512.0,
                       "loss": round(float(logs["loss"]), 4)})
"""

LINT = """
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.amp import auto_cast
from paddle_tpu.jit import InputSpec
from paddle_tpu.jit.dy2static.program_translator import ProgramTranslator
from paddle_tpu.static.passes import pass_base
from paddle_tpu.static.passes.amp_lint import AmpLintPass


def lint(fn, spec, feeds):
    prog, _, fetch = ProgramTranslator().get_program(fn, spec)
    res = pass_base.PassResult("amp_lint")
    AmpLintPass().run(prog, pass_base.PassContext(
        feed_shapes={k: tuple(s) for k, (s, _) in feeds.items()},
        feed_dtypes={k: d for k, (_, d) in feeds.items()},
        fetch_names=[v.name for v in fetch]), res)
    amp = [d.code for d in res.diagnostics if d.code.startswith("AMP")]
    return prog, amp, res.cast_plan


paddle.seed(0)
net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.LayerNorm(32),
                    nn.Linear(32, 8))
ce = nn.CrossEntropyLoss()


def train_fwd(x, y):
    with auto_cast(level="O1", dtype="bfloat16"):
        out = net(x)
    return ce(out, y)


prog, amp, plan = lint(
    train_fwd,
    [InputSpec([4, 16], "float32", name="x"),
     InputSpec([4], "int64", name="y")],
    {"x": ((4, 16), "float32"), "y": ((4,), "int64")})
assert amp == [], f"auto_cast-captured train program lints dirty: {amp}"
lists = plan.to_auto_cast_lists()
assert "linear" in lists["custom_white_list"], (
    f"cast plan white list misses the matmul class: {lists}")

# negative control: bf16 fed straight into a black-list reduction must
# trip AMP01 — the lint actually sees the narrowed dtype flow
w16 = paddle.to_tensor(
    np.random.RandomState(0).rand(16, 32).astype("float32")
).astype("bfloat16")


def narrowed(x):
    return paddle.mean(paddle.matmul(x, w16))


_, amp_bad, _ = lint(
    narrowed, [InputSpec([4, 16], "bfloat16", name="x")],
    {"x": ((4, 16), "bfloat16")})
assert "AMP01" in amp_bad, (
    f"lint missed a bf16-narrowed black-list op: {amp_bad}")
print("lint leg ok:", {"train_findings": amp, "cast_plan": lists,
                       "narrowed_findings": amp_bad})
"""


def run_leg(name, code):
    with tempfile.TemporaryDirectory(prefix=f"amp_{name}_") as d:
        env = dict(os.environ)
        env["PADDLE_FLIGHT_DIR"] = os.path.join(d, "flight")
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        p = subprocess.run(
            [sys.executable, "-c", textwrap.dedent(code)],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=600)
        sys.stdout.write(p.stdout)
        if p.returncode != 0:
            sys.stderr.write(p.stderr)
            print(f"amp_gate: {name} leg FAILED", file=sys.stderr)
            return False
        return True


def main():
    ok = run_leg("parity", PARITY)
    ok = run_leg("fp16", FP16) and ok
    ok = run_leg("lint", LINT) and ok
    if not ok:
        return 1
    print("amp_gate: all legs passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Per-op device profile of the flagship BERT-base training step.

Runs the exact bench.py config under jax.profiler.trace and converts the
xplane capture to an HLO-op-level time breakdown (xprof's hlo_op_stats),
printing a table of where the step's wall-clock actually goes — the
profile the round-3 verdict asked to commit alongside BASELINE.md.

Usage: python tools/profile_bert.py [--remat ctx] [--batch 128] [--steps 5]
"""
import argparse
import glob
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(remat: str, batch: int, steps: int, logdir: str,
            seq: int = 512) -> float:
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.topology import build_mesh
    from paddle_tpu.models import GPTConfig
    from paddle_tpu.models.gpt_spmd import build_spmd_train_step

    cfg = GPTConfig(vocab_size=30528, hidden_size=768, num_layers=12,
                    num_heads=12, max_seq_len=seq)
    mesh = build_mesh({"dp": 1}, devices=jax.devices()[:1])
    step, init_fn = build_spmd_train_step(cfg, mesh,
                                          compute_dtype=jnp.bfloat16,
                                          remat_policy=remat)
    params, opt_state = init_fn(seed=0)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    loss, params, opt_state = step(params, opt_state, ids, labels)
    float(loss)
    jax.block_until_ready(params)

    import time
    with jax.profiler.trace(logdir):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss, params, opt_state = step(params, opt_state, ids, labels)
        float(loss)
        jax.block_until_ready(params)
        dt = time.perf_counter() - t0
    print(f"[capture] {steps} steps in {dt:.3f}s -> "
          f"{batch * steps / dt:.1f} seq/s", file=sys.stderr)
    return dt


def summarize(logdir: str, top: int = 40):
    from xprof.convert import raw_to_tool_data as rtd
    paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        print("no xplane.pb captured", file=sys.stderr)
        return None
    data, _ = rtd.xspace_to_tool_data([paths[-1]], "hlo_stats", {})
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")
    return data


def print_table(text: str, top: int):
    # gviz JSON: {"cols": [{id,label},...], "rows": [{"c": [{"v": ...}]}]}
    tbl = json.loads(text)
    ids = [c["id"] for c in tbl["cols"]]
    agg = {}
    for row in tbl["rows"]:
        r = {i: (c or {}).get("v") for i, c in zip(ids, row["c"])}
        cat = r.get("category") or "?"
        name = (r.get("hlo_op_expression") or r.get("hlo_op_name") or "?")
        t = float(r.get("total_self_time") or 0.0)
        occ = int(r.get("occurrences") or 0)
        key = (cat, name[:110])
        a = agg.setdefault(key, [0.0, 0])
        a[0] += t
        a[1] += occ
    total = sum(a[0] for a in agg.values()) or 1.0
    print(f"{'%':>6} {'self-us':>12} {'occ':>6}  category / op")
    cat_tot = {}
    for (cat, _), a in agg.items():
        cat_tot[cat] = cat_tot.get(cat, 0.0) + a[0]
    print("== by category ==")
    for cat, t in sorted(cat_tot.items(), key=lambda kv: -kv[1]):
        print(f"{100*t/total:6.2f} {t:12.0f}        {cat}")
    print("== top ops ==")
    for (cat, name), a in sorted(agg.items(), key=lambda kv: -kv[1][0])[:top]:
        print(f"{100*a[0]/total:6.2f} {a[0]:12.0f} {a[1]:6d}  [{cat}] {name}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--remat", default="ctx")
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--logdir", default="/tmp/bert_profile")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--reuse", action="store_true",
                    help="skip capture, summarize existing logdir")
    args = ap.parse_args()
    if not args.reuse:
        os.makedirs(args.logdir, exist_ok=True)
        capture(args.remat, args.batch, args.steps, args.logdir, args.seq)
    data = summarize(args.logdir)
    if data:
        print_table(data, args.top)


if __name__ == "__main__":
    main()

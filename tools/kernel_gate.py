"""CI gate for the r10 kernel work (fused conv blocks, fused optimizer,
int8 serving artifacts).

Legs:
1. **Fit loss parity** — fixed-seed 10-step ResNet18 fit with
   FLAGS_fused_conv + FLAGS_fused_optimizer ON vs OFF: step-1 loss must
   match to float32 noise (the fused forward is bit-exact), the whole
   trajectory within tolerance (the custom-vjp backward reassociates
   the BN reduction chain), and both runs must end finite.
2. **Dispatch/executable bound** — one conv+bn+relu block dispatches as
   ONE op (one eager-jit executable), not three, with the flag on; the
   escape hatch restores the 3-op composition.
3. **Fused-optimizer parity** — Momentum/Adam/AdamW (incl. weight decay
   + an LR schedule) eager-trained fused vs per-leaf: params allclose
   at 1e-6, and the fused path is bit-deterministic (two fused runs
   produce identical param sha256s).
4. **Int8 artifact serve** — a resnet18 jit.save artifact loads at
   PrecisionType.Int8 through the InferenceEngine (bucketing +
   ExecutableCache) and serves with top-1 agreement vs fp32 and
   bounded compiles.

Exit code 0 = gate passed.
"""
import hashlib
import os
import sys
import tempfile
import warnings

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

FAILURES = []


def check(name, ok, detail=""):
    status = "ok" if ok else "FAIL"
    print(f"[kernel_gate] {name}: {status} {detail}")
    if not ok:
        FAILURES.append(name)


def leg_fit_parity():
    import paddle_tpu as paddle
    from paddle_tpu.utils import flags as fl

    def run(fused):
        paddle.seed(7)
        fl.set_flags({"FLAGS_fused_conv": fused,
                      "FLAGS_fused_optimizer": fused})
        net = paddle.vision.models.resnet18(num_classes=10)
        model = paddle.Model(net)
        opt = paddle.optimizer.Momentum(
            learning_rate=0.01, momentum=0.9,
            parameters=net.parameters())
        model.prepare(opt, paddle.nn.CrossEntropyLoss())
        rng = np.random.RandomState(7)
        x = np.asarray(rng.rand(4, 3, 32, 32), np.float32)
        y = np.asarray(rng.randint(0, 10, (4, 1)), np.int32)
        return [float(model.train_batch([x], [y])["loss"])
                for _ in range(10)]

    on = run(True)
    off = run(False)
    check("fit.finite", all(np.isfinite(on)) and all(np.isfinite(off)),
          f"on[-1]={on[-1]:.5f} off[-1]={off[-1]:.5f}")
    check("fit.step1_parity", abs(on[0] - off[0]) <= 1e-5,
          f"{on[0]:.7f} vs {off[0]:.7f}")
    rel = max(abs(a - b) / max(abs(b), 1e-3) for a, b in zip(on, off))
    check("fit.trajectory_parity", rel < 0.05,
          f"max rel step diff {rel:.4f} (tol 0.05)")


def leg_dispatch_bound():
    import paddle_tpu as paddle
    from paddle_tpu.profiler import tracer
    from paddle_tpu.utils import flags as fl
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    conv = nn.Conv2D(3, 8, 3, padding=1, bias_attr=False)
    bn = nn.BatchNorm2D(8)
    x = paddle.to_tensor(
        np.random.RandomState(0).rand(2, 3, 16, 16).astype("float32"))

    def ops_for(fused):
        fl.set_flags({"FLAGS_fused_conv": fused})
        F.fused_conv_bn(x, conv, bn, act="relu")   # warm factory/caches
        tracer.enable()
        tracer.clear()
        F.fused_conv_bn(x, conv, bn, act="relu")
        table = tracer.op_table()
        tracer.disable()
        tracer.clear()
        return set(table)

    fused_ops = ops_for(True)
    eager_ops = ops_for(False)
    fl.set_flags({"FLAGS_fused_conv": True})
    check("block.one_dispatch",
          fused_ops == {"fused_conv_bn_relu"},
          f"fused block dispatched {sorted(fused_ops)}")
    check("block.escape_hatch",
          eager_ops == {"conv2d", "batch_norm", "relu"},
          f"FLAGS_fused_conv=0 dispatched {sorted(eager_ops)}")


def leg_optimizer_parity():
    import paddle_tpu as paddle
    from paddle_tpu.utils import flags as fl
    import paddle_tpu.nn as nn

    def train(opt_name, fused, kw):
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 16), nn.ReLU(),
                            nn.Linear(16, 4))
        sched = paddle.optimizer.lr.StepDecay(0.05, step_size=2,
                                              gamma=0.5)
        opt = getattr(paddle.optimizer, opt_name)(
            learning_rate=sched, parameters=net.parameters(), **kw)
        fl.set_flags({"FLAGS_fused_optimizer": fused})
        rng = np.random.RandomState(3)
        xb = paddle.to_tensor(rng.rand(16, 8).astype("float32"))
        yb = paddle.to_tensor(rng.rand(16, 4).astype("float32"))
        for _ in range(5):
            loss = paddle.mean((net(xb) - yb) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            sched.step()
        arrs = [np.asarray(p.numpy()) for p in net.parameters()]
        sha = hashlib.sha256(
            b"".join(a.tobytes() for a in arrs)).hexdigest()
        return arrs, sha

    for name, kw in (("Momentum", dict(momentum=0.9,
                                       weight_decay=0.01)),
                     ("Adam", dict(weight_decay=0.02)),
                     ("AdamW", dict(weight_decay=0.01))):
        fused1, sha1 = train(name, True, kw)
        fused2, sha2 = train(name, True, kw)
        ref, _ = train(name, False, kw)
        md = max(np.abs(a - b).max() for a, b in zip(fused1, ref))
        check(f"opt.{name}.parity", md < 1e-6,
              f"max param diff vs per-leaf {md:.2e}")
        check(f"opt.{name}.sha_deterministic", sha1 == sha2,
              sha1[:12])
    fl.set_flags({"FLAGS_fused_optimizer": True})


def leg_int8_serve():
    import paddle_tpu as paddle
    from paddle_tpu import inference, serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.profiler import metrics as pm

    paddle.seed(0)
    net = paddle.vision.models.resnet18(num_classes=10)
    net.eval()
    prefix = os.path.join(tempfile.mkdtemp(prefix="kernel_gate_"), "m")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        paddle.jit.save(net, prefix, input_spec=[
            InputSpec([4, 3, 32, 32], "float32", name="x")])

    rng = np.random.RandomState(0)
    batches = [rng.rand(4, 3, 32, 32).astype("float32")
               for _ in range(4)]
    ref = [inference.Predictor(inference.Config(prefix))
           .run(inputs=[b])[0] for b in batches]

    cfg = inference.Config(prefix)
    cfg.set_precision(inference.PrecisionType.Int8)
    eng = serving.InferenceEngine(cfg, serving.EngineConfig(
        max_batch_size=4, min_batch_bucket=4, num_workers=1,
        name="kernel_gate_int8"))
    outs = [eng.infer([b], timeout=600)[0] for b in batches]
    compiles = pm.counter("kernel_gate_int8.compile").value
    eng.close()
    agree = float(np.mean([np.mean(np.argmax(a, 1) == np.argmax(b, 1))
                           for a, b in zip(ref, outs)]))
    check("int8.top1_agreement", agree >= 0.9, f"{agree:.3f}")
    check("int8.compile_bound", 0 < compiles <= 1,
          f"{compiles} compiles for one bucket")


def main():
    leg_fit_parity()
    leg_dispatch_bound()
    leg_optimizer_parity()
    leg_int8_serve()
    if FAILURES:
        print(f"[kernel_gate] FAILED: {FAILURES}")
        return 1
    print("[kernel_gate] all legs passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())

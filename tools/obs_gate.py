#!/usr/bin/env python
"""CI observability gate: request tracing, fleet aggregation, flight
recorder — the three ISSUE-12 layers, end to end.

Phase 1 (traced burst, run TWICE in fresh processes for
bit-stability): a GenerationEngine behind the HTTP server answers a
request sent with a fixed W3C ``traceparent`` — the response must echo
the same trace_id, and the exported trace must contain the complete
ingress -> admission -> queue_wait -> prefill -> >=1 decode -> egress
chain with correct parent/child links plus fan-in ``batch::*`` spans.
The span-chain structure and the generated tokens must be IDENTICAL
across the two runs.  The same subprocess first pins the zero-cost
contract: with tracing off (and the flight recorder disabled) a full
request leaves zero rtrace spans and zero flight events — the hooks
are a single predicate read.

Phase 2 (fleet): a supervised 2-rank elastic fit
(``--supervise --np 1:2``) under a fixed ``host.slow`` chaos spec,
with the supervisor's aggregated ``/metrics`` endpoint armed.  While
both ranks run, the aggregated endpoint must serve BOTH ranks'
rank-labeled series plus fleet rollups.  Rank 1 SIGKILLs itself
mid-run: the supervisor signals the survivor before killing the gang,
so rank 0's flight dump lands in PADDLE_FLIGHT_DIR with its chaos
injections at EXACT counts; the supervisor's own dump carries the
rendezvous rounds; both fold into PADDLE_SUPERVISE_REPORT.  The two
per-rank chrome traces exported before the kill must merge into one
timeline with one lane per rank, clock-aligned.

Wired into tools/run_all_tests.sh.
"""
import http.client
import json
import os
import socket
import subprocess
import sys
import tempfile
import textwrap
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

TID = "ab" * 16
PARENT = "12" * 8

BURST = """
import json, os, sys, threading, time
import numpy as np
import http.client
import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models import GPT, GPTConfig
from paddle_tpu.profiler import flight, rtrace, tracer

TID = %(tid)r
PARENT = %(parent)r
out_path = sys.argv[1]

paddle.seed(0)
net = GPT(GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, ffn_mult=2))
eng = serving.GenerationEngine(net, serving.GenerationEngineConfig(
    max_slots=4, max_length=64, max_new_tokens=6, name="obsgate"))

# -- zero-cost pin: tracing off + recorder off => zero events ---------
paddle.set_flags({"FLAGS_flight_recorder": 0})
flight.clear()
tracer.clear()
assert not rtrace.active
eng.generate([11, 12, 13], max_new_tokens=2, timeout=300)
assert [e for e in tracer.events() if e[4] == "rtrace"] == [], \\
    "rtrace spans recorded with tracing off"
assert flight.events() == [], "flight events recorded while disabled"
paddle.set_flags({"FLAGS_flight_recorder": 1})

# -- traced burst ------------------------------------------------------
rtrace.enable()
res = {}
with serving.ServingServer(eng) as srv:
    def other(i):
        time.sleep(0.01 * i)
        conn = http.client.HTTPConnection(srv.host, srv.port,
                                          timeout=300)
        conn.request("POST", "/v1/generate", json.dumps(
            {"prompt_ids": [20 + i, 21, 22], "max_new_tokens": 6}),
            {"Content-Type": "application/json"})
        conn.getresponse().read()
        conn.close()
    ts = [threading.Thread(target=other, args=(i,)) for i in (1, 2)]
    for t in ts:
        t.start()
    conn = http.client.HTTPConnection(srv.host, srv.port, timeout=300)
    conn.request("POST", "/v1/generate", json.dumps(
        {"prompt_ids": [3, 5, 7], "max_new_tokens": 6, "seed": 0}),
        {"Content-Type": "application/json",
         "traceparent": f"00-{TID}-{PARENT}-01",
         "X-Request-Id": "obsgate-req"})
    r = conn.getresponse()
    echoed = r.getheader("traceparent")
    rid = r.getheader("X-Request-Id")
    tokens = json.loads(r.read())["tokens"]
    conn.close()
    for t in ts:
        t.join()
eng.close()

assert r.status == 200
assert echoed.split("-")[1] == TID, f"trace_id not echoed: {echoed}"
assert rid == "obsgate-req"

spans = rtrace.request_spans(trace_id=TID)
by_name = {}
chain = []
root = None
for s in spans:
    if s["name"] == "ingress":
        root = s["span_id"]
by_name = {s["name"]: s for s in spans}
assert root is not None, "no ingress span"
assert by_name["ingress"]["parent_id"] == PARENT
for name in ("admission", "queue_wait", "prefill", "decode",
             "egress"):
    assert name in by_name, f"missing span {name}"
    assert by_name[name]["parent_id"] == root, \\
        f"{name} not parented to ingress"
assert by_name["admission"]["outcome"] == "admitted"
n_decode = sum(1 for s in spans if s["name"] == "decode")
assert n_decode >= 1
for s in spans:
    if s["name"] == "decode":
        assert "batch_span" in s
# fan-in: every decode's batch span links this trace back
links = {(e[5] or {}).get("span_id"): e[5] for e in tracer.events()
         if e[4] == "rtrace" and e[0].startswith("batch::")}
for s in spans:
    if s["name"] == "decode":
        b = links[s["batch_span"]]
        assert any(l["trace_id"] == TID for l in b["links"])

chain = [(s["name"],
          s.get("parent_id") == root or s["name"] == "ingress",
          s.get("outcome"), bool(s.get("terminated")))
         for s in spans]
with open(out_path, "w") as f:
    json.dump({"trace_id": TID, "tokens": tokens, "chain": chain,
               "n_decode": n_decode}, f)
print("burst ok:", [c[0] for c in chain])
"""

TRAINER = """
import json, os, signal
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed import fleet_metrics as fm
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.profiler import tracer

gen = int(os.environ.get("PADDLE_RESTART_GENERATION", "0"))
rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
work = os.environ["OBS_GATE_DIR"]

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.Tanh(),
                           paddle.nn.Linear(8, 1))
model = paddle.Model(net)
opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
model.prepare(opt, paddle.nn.MSELoss())
tracer.enable()


class DS(paddle.io.Dataset):
    def __getitem__(self, i):
        import time
        time.sleep(0.05)
        rng = np.random.RandomState(i)
        x = rng.rand(4).astype("float32")
        return x, (x.sum(keepdims=True) * 0.5).astype("float32")

    def __len__(self):
        return 64           # batch 4 -> 16 steps per epoch


class Obs(Callback):
    def on_train_batch_end(self, step, logs=None):
        if step == 4:
            # per-rank chrome trace for the supervisor-side merge
            fm.write_rank_trace(
                os.path.join(work, f"trace.r{rank}.g{gen}.json"),
                rank=rank)
        if rank == 1 and gen == 0 and step == 8:
            os.kill(os.getpid(), signal.SIGKILL)


model.fit(DS(), batch_size=4, epochs=1, verbose=0, shuffle=False,
          callbacks=[Obs()])
with open(os.path.join(work, f"done.r{rank}.g{gen}"), "w") as f:
    f.write("ok")
"""

CHAOS_SPEC = "host.slow:delay=0.01@2-3"     # exactly 2 injections/proc


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_burst(work, tag):
    script = os.path.join(work, "burst.py")
    with open(script, "w") as f:
        f.write(BURST % {"tid": TID, "parent": PARENT})
    out = os.path.join(work, f"burst.{tag}.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               PADDLE_THREAD_CANARY="0")
    # share the AOT cache between the two runs so run 2 is fast
    env.setdefault("FLAGS_compile_cache_dir",
                   os.path.join(work, "aot"))
    r = subprocess.run([sys.executable, script, out], env=env,
                       cwd=REPO, capture_output=True, text=True,
                       timeout=900)
    if r.returncode != 0:
        print(r.stdout[-3000:], file=sys.stderr)
        print(r.stderr[-3000:], file=sys.stderr)
        raise SystemExit(f"obs gate: traced burst {tag} failed "
                         f"(rc={r.returncode})")
    return json.load(open(out))


def scrape_both_ranks(port, proc, deadline_s=120):
    """Poll the aggregated /metrics until both ranks' labeled series
    appear (they publish on the heartbeat cadence)."""
    t0 = time.monotonic()
    last = ""
    while time.monotonic() - t0 < deadline_s and proc.poll() is None:
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=5)
            conn.request("GET", "/metrics")
            r = conn.getresponse()
            ctype = r.getheader("Content-Type")
            last = r.read().decode()
            conn.close()
            if 'rank="0"' in last and 'rank="1"' in last:
                assert ctype == "text/plain; version=0.0.4", ctype
                return last
        except (OSError, http.client.HTTPException):
            pass
        time.sleep(0.05)
    raise SystemExit(
        "obs gate: aggregated /metrics never showed both ranks "
        f"(supervisor rc={proc.poll()}); last scrape:\n{last[-2000:]}")


def main():
    work = tempfile.mkdtemp(prefix="obs_gate_")

    # -- phase 1: traced burst, twice, bit-stable ----------------------
    a = run_burst(work, "run1")
    b = run_burst(work, "run2")
    assert a["chain"] == b["chain"], \
        f"span chains differ across runs:\n{a['chain']}\n{b['chain']}"
    assert a["tokens"] == b["tokens"], "tokens differ across runs"
    assert a["n_decode"] >= 1
    names = [c[0] for c in a["chain"]]
    assert names[0] == "ingress" and names[-1] == "egress"

    # -- phase 2: supervised 2-rank fleet ------------------------------
    trainer = os.path.join(work, "trainer.py")
    with open(trainer, "w") as f:
        f.write(textwrap.dedent(TRAINER))
    report = os.path.join(work, "report.json")
    flight_dir = os.path.join(work, "flight")
    port = free_port()
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               FLAGS_chaos_spec=CHAOS_SPEC,
               FLAGS_straggler_factor="0",   # equal delays != straggler
               OBS_GATE_DIR=work,
               PADDLE_HEARTBEAT_INTERVAL="0.05",
               PADDLE_SUPERVISE_REPORT=report,
               PADDLE_FLIGHT_DIR=flight_dir,
               PADDLE_FLEET_METRICS_PORT=str(port))
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--supervise", "--np", "1:2", "--nproc", "2",
         "--max_restarts", "1", trainer],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        agg = scrape_both_ranks(port, proc)
        out, err = proc.communicate(timeout=600)
    except BaseException:
        proc.kill()
        out, err = proc.communicate()
        print(out[-3000:], file=sys.stderr)
        print(err[-3000:], file=sys.stderr)
        raise
    if proc.returncode != 0:
        print(out[-3000:], file=sys.stderr)
        print(err[-3000:], file=sys.stderr)
        raise SystemExit(f"obs gate: supervised launch failed "
                         f"(rc={proc.returncode})")

    # aggregated rollup present (both ranks' hapi series + fleet stat)
    assert "_fleet{stat=" in agg or "_fleet_count{stat=" in agg, \
        "no fleet rollup series in aggregated /metrics"

    rep = json.load(open(report))
    assert rep["kind"] == "done", rep
    # SIGKILL == host loss: one shrink (2 -> 1), no budget spent
    assert rep["shrinks"] == 1 and rep["restarts"] == 0, rep
    assert rep["world_history"] == [2, 1], rep

    dumps = rep["flight_dumps"]
    # the survivor (rank 0, generation 0) dumped on the pre-kill
    # SIGUSR1 — its tail must hold the chaos injections, exact count
    surv = dumps.get("flight.r0.g0.json")
    assert surv is not None, f"no survivor flight dump: {list(dumps)}"
    assert surv["events"] > 0, surv
    assert surv["counts"].get("chaos.host.slow") == 2, surv["counts"]
    assert surv["counts"].get("launch.fit_start") == 1, surv["counts"]
    # the supervisor's own dump: one rendezvous note per gang
    # formation, matching the report's counter
    sup = dumps.get("flight.supervisor.json")
    assert sup is not None, f"no supervisor flight dump: {list(dumps)}"
    assert sup["counts"].get("launch.rendezvous") == \
        rep["rendezvous_rounds"] == 2, (sup["counts"], rep)

    # merged rank-laned timeline from the pre-kill per-rank traces
    from paddle_tpu.distributed import fleet_metrics as fm
    docs = []
    for r in (0, 1):
        p = os.path.join(work, f"trace.r{r}.g0.json")
        assert os.path.exists(p), f"rank {r} never exported its trace"
        docs.append(json.load(open(p)))
    merged = fm.merge_chrome_traces(docs)
    lanes = {e["pid"] for e in merged["traceEvents"]
             if e.get("ph") == "X"}
    assert lanes == {0, 1}, f"expected one lane per rank, got {lanes}"
    assert merged["metadata"]["aligned"] is True

    print(f"obs gate OK: chain={names}, tokens={a['tokens']}, "
          f"shrinks={rep['shrinks']}, "
          f"rendezvous={rep['rendezvous_rounds']}, "
          f"survivor_dump={surv['events']} events "
          f"(chaos.host.slow={surv['counts']['chaos.host.slow']}), "
          f"merged lanes={sorted(lanes)}")


if __name__ == "__main__":
    main()

"""Device-truthful micro-benchmark timing for the shared axon chip.

Wall-clock through the tunnel swings 2-5x with other tenants' load;
per-op device self times from an xprof capture do not.  ``device_time``
runs a chained loop (data dependence through a scalar carry) inside ONE
jit, captures a trace of it, and returns summed device self-time per
iteration.
"""
import glob
import json
import os
import shutil
import tempfile
import time

import jax
import jax.numpy as jnp
from jax import lax


def chain_run(f, args, weights=(), iters=8):
    """Build + compile the chained loop; returns run(args, weights).
    The loop body consumes run's PARAMETERS (not closure constants —
    baked-in arrays would let XLA treat the whole loop as a constant
    and would ignore re-invocations with fresh data)."""
    @jax.jit
    def run(args, weights):
        def body(c, _):
            out = f(*[a + c.astype(a.dtype) for a in args], *weights)
            leaves = jax.tree_util.tree_leaves(out)
            return sum(jnp.sum(o.astype(jnp.float32))
                       for o in leaves) * 1e-20, None
        c, _ = lax.scan(body, jnp.zeros(()), None, length=iters)
        return c

    return run


def device_time(f, args, weights=(), iters=8):
    """Seconds of device self-time per iteration of ``f(*args,
    *weights)`` (trace-measured; contention-immune)."""
    from xprof.convert import raw_to_tool_data as rtd
    run = chain_run(f, args, weights, iters)
    float(run(args, weights))            # compile + warm outside capture
    logdir = tempfile.mkdtemp(prefix="devbench_")
    try:
        with jax.profiler.trace(logdir):
            float(run(args, weights))
        paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                                 recursive=True))
        if not paths:
            return None
        data, _ = rtd.xspace_to_tool_data([paths[-1]], "hlo_stats", {})
        if isinstance(data, bytes):
            data = data.decode()
        tbl = json.loads(data)
        ids = [c["id"] for c in tbl["cols"]]
        total = 0.0
        for row in tbl["rows"]:
            r = {i: (c or {}).get("v") for i, c in zip(ids, row["c"])}
            total += float(r.get("total_self_time") or 0.0)
        return total / 1e6 / iters
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


def wall_time(f, args, weights=(), iters=8):
    run = chain_run(f, args, weights, iters)
    float(run(args, weights))
    t0 = time.perf_counter()
    float(run(args, weights))
    return (time.perf_counter() - t0) / iters

#!/usr/bin/env python
"""CI serving gate: the InferenceEngine under concurrent synthetic
clients with a FIXED chaos spec must lose nothing, shed exactly what
admission control says it shed, and compile no more executables than
the bucket count allows.

Three phases:

1. soak — 4 client threads x 8 requests of randomized batch sizes under
   ``serve.request:fail@7`` (the 7th admission, globally, is injected to
   fail): every request must either succeed bit-exactly vs a reference
   ``Predictor.run`` or be the single injected ChaosError; zero lost.
2. overload — queue paused, ``max_queue`` requests parked, the next R
   submits must each be rejected with ``queue_full`` (exact shed
   count), then the parked requests must all complete after resume.
3. accounting — total XLA compiles (``serving.compile``) <= the bucket
   count; completed + failed + rejected + shed tallies exactly match
   what was submitted.

Wired into tools/run_all_tests.sh next to the chaos gate.
"""
import os
import sys
import tempfile
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

CHAOS_SPEC = "serve.request:fail@7"
CLIENTS, PER_CLIENT = 4, 8
OVERLOAD_EXTRA = 3


def val(name):
    from paddle_tpu.profiler import metrics
    m = metrics.get(name)
    return m.value if m is not None else 0


def main():
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.utils import chaos

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    prefix = os.path.join(tempfile.mkdtemp(prefix="serving_gate_"), "m")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec([-1, 8], "float32", name="x")])
    reference = paddle.inference.create_predictor(
        paddle.inference.Config(prefix))

    engine = serving.InferenceEngine(prefix, serving.EngineConfig(
        max_batch_size=8, batch_timeout_ms=5, num_workers=2,
        max_queue=16))

    # -- phase 1: chaos soak ------------------------------------------
    paddle.set_flags({"FLAGS_chaos_spec": CHAOS_SPEC})
    ok, injected, lost = [], [], []

    def client(tid):
        rng = np.random.RandomState(100 + tid)
        for _ in range(PER_CLIENT):
            # rows 2..8: batched rows are bit-identical to unbatched
            # runs for M >= 2 (XLA's M=1 gemv specialization is the one
            # batch-size-dependent path; rows=1 ulp semantics are
            # covered in tests/test_serving.py)
            x = rng.rand(int(rng.randint(2, 9)), 8).astype("float32")
            try:
                out, = engine.infer([x], timeout=120)
            except chaos.ChaosError:
                injected.append(tid)
                continue
            except Exception as e:  # anything else is a lost request
                lost.append(repr(e))
                continue
            if not np.array_equal(out, reference.run([x])[0]):
                lost.append(f"output mismatch (client {tid})")
            else:
                ok.append(tid)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    paddle.set_flags({"FLAGS_chaos_spec": ""})

    total = CLIENTS * PER_CLIENT
    assert not lost, f"lost/wrong requests: {lost}"
    assert len(injected) == 1, \
        f"expected exactly 1 injected failure, got {len(injected)}"
    assert len(ok) == total - 1, (len(ok), total)
    assert val("chaos.injected.serve.request") == 1

    # -- phase 2: deterministic overload ------------------------------
    rej0 = val("serving.request.rejected.queue_full")
    engine.pause()
    x = np.zeros((1, 8), np.float32)
    parked = [engine.submit([x]) for _ in range(engine.config.max_queue)]
    shed = 0
    for _ in range(OVERLOAD_EXTRA):
        try:
            engine.submit([x])
        except serving.RequestRejected as e:
            assert e.reason == "queue_full", e.reason
            shed += 1
    assert shed == OVERLOAD_EXTRA, shed
    assert val("serving.request.rejected.queue_full") - rej0 \
        == OVERLOAD_EXTRA
    engine.resume()
    for f in parked:                      # parked work survives overload
        assert f.result(timeout=120)[0].shape == (1, 4)

    # -- phase 3: accounting ------------------------------------------
    compiles = val("serving.compile")
    bucket_bound = engine._policy.max_buckets()
    assert compiles <= bucket_bound, \
        f"{compiles} compiles > bucket bound {bucket_bound}"
    completed = val("serving.request.completed")
    assert completed == (total - 1) + engine.config.max_queue, completed
    engine.close()
    occ = engine.stats()["serving.batch.occupancy"]
    print(f"serving gate OK: {completed} served bit-exact, "
          f"1 chaos-injected, {shed} shed at full queue, "
          f"{compiles} compiles <= {bucket_bound} buckets, "
          f"mean batch occupancy {occ['avg']:.2f}")


if __name__ == "__main__":
    main()

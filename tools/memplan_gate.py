#!/usr/bin/env python
"""CI static-analysis gate for the memory planner, remat policy pass
and amp lint — the ISSUE-17 layer, end to end in fresh subprocesses.

Phase 1 (planner calibration): capture the golden GPT and resnet18
serving forwards through dy2static, build the static memory plan, and
replay each program eagerly under memscope.  The planner's peak
estimate must land within +-15% of the measured peak on both eval
programs, the plan doc must round-trip through ``trace_summary.py
--memplan``, and ``amp_lint`` must report ZERO AMP findings on these
all-fp32 programs.

Phase 2 (remat): a remat-friendly tanh-chain train program is
rewritten under ``FLAGS_remat_budget_mb``.  Acceptance is behavioral,
not estimated: loss AND input-gradient stay bit-exact through the
Executor, and the memscope-MEASURED replay peak strictly drops.  The
estimate check stays on the pre-remat program only (the planner's
``__remat_internal_bytes__`` transient models the in-op recompute
window, which boundary sampling cannot observe).

Wired into tools/run_all_tests.sh.
"""
import json
import os
import subprocess
import sys
import tempfile
import textwrap

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

GOLDEN = """
import json, os, sys
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.jit import InputSpec
from paddle_tpu.jit.dy2static.program_translator import ProgramTranslator
from paddle_tpu.models import GPT, GPTConfig
from paddle_tpu.static.passes import pass_base
from paddle_tpu.static.passes.amp_lint import AmpLintPass
from paddle_tpu.static.passes.memory_plan import (build_memory_plan,
                                                  measured_replay)

plan_path = sys.argv[1]
paddle.seed(0)
pt = ProgramTranslator()
rng = np.random.RandomState(0)

gpt = GPT(GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=2, max_seq_len=64, ffn_mult=2))
gpt.eval()


def gpt_serve(ids):
    return F.softmax(gpt.forward(ids), axis=-1)


resnet = paddle.vision.models.resnet18(num_classes=10)
resnet.eval()


def resnet_serve(img):
    return F.softmax(resnet.forward(img), axis=-1)


legs = [
    ("gpt", gpt_serve, [InputSpec([2, 32], "int32", name="ids")],
     {"ids": rng.randint(0, 256, (2, 32)).astype("int32")}),
    ("resnet18", resnet_serve,
     [InputSpec([2, 3, 32, 32], "float32", name="img")],
     {"img": rng.rand(2, 3, 32, 32).astype("float32")}),
]

docs = {}
for name, fn, spec, feed in legs:
    prog, _, fetch = pt.get_program(fn, spec)
    fetch_names = [v.name for v in fetch]
    shapes = {k: tuple(v.shape) for k, v in feed.items()}
    dtypes = {k: str(v.dtype) for k, v in feed.items()}

    plan = build_memory_plan(prog, feed_shapes=shapes,
                             feed_dtypes=dtypes, fetch_names=fetch_names)
    meas = measured_replay(prog, feed, fetch_names)
    ratio = plan.peak_bytes / meas["peak_bytes"]
    assert 0.85 <= ratio <= 1.15, (
        f"{name}: planner est {plan.peak_bytes}B vs measured "
        f"{meas['peak_bytes']}B — est/measured {ratio:.3f} outside "
        "the +-15% golden-eval band")

    res = pass_base.PassResult("amp_lint")
    AmpLintPass().run(prog, pass_base.PassContext(
        feed_shapes=shapes, feed_dtypes=dtypes,
        fetch_names=fetch_names), res)
    amp = [d.code for d in res.diagnostics if d.code.startswith("AMP")]
    assert amp == [], f"{name}: fp32 golden program lints dirty: {amp}"
    assert res.cast_plan is not None, f"{name}: no cast plan emitted"

    docs[name] = {"ratio": round(ratio, 3),
                  "est": int(plan.peak_bytes),
                  "measured": int(meas["peak_bytes"])}
    if name == "gpt":
        with open(plan_path, "w") as f:
            json.dump(plan.to_doc(), f)

print("golden leg ok:", docs)
"""

REMAT = """
import sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static.passes import pass_base
from paddle_tpu.static.passes.memory_plan import (build_memory_plan,
                                                  measured_replay)
from paddle_tpu.static.passes.remat import RematPass

paddle.enable_static()
main, startup = static.Program(), static.Program()
with static.program_guard(main, startup):
    x = static.data("x", [512, 512], "float32")
    x.stop_gradient = False
    h = x
    for _ in range(6):
        h = paddle.tanh(h)
    loss = paddle.mean(paddle.square(h))
    (gx,) = static.gradients(loss, [x])

exe = static.Executor()
exe.run(startup)
feed = {"x": np.random.RandomState(0).rand(512, 512).astype("float32")}
fetch = [loss.name, gx.name]
shapes = {n: v.shape for n, v in feed.items()}

plan0 = build_memory_plan(main, feed_shapes=shapes, fetch_names=fetch)
meas0 = measured_replay(main, feed, fetch)
ratio0 = plan0.peak_bytes / meas0["peak_bytes"]
assert 0.6 <= ratio0 <= 1.4, (
    f"pre-remat train est/measured {ratio0:.3f} outside the train band")
ref = [np.asarray(a) for a in exe.run(main, feed=feed, fetch_list=fetch)]

paddle.set_flags({"FLAGS_remat_budget_mb": 4})
res = pass_base.PassResult("program_remat")
RematPass().run(main, pass_base.PassContext(
    feed_shapes=shapes, fetch_names=fetch), res)
rw = res.program
assert rw is not None and rw is not main, "remat refused a tanh chain"
assert any(op.attrs.get("__remat__") for op in rw.ops)

plan1 = build_memory_plan(rw, feed_shapes=shapes, fetch_names=fetch)
assert plan1.peak_bytes < plan0.peak_bytes, (
    f"estimated peak did not drop: {plan0.peak_bytes} -> "
    f"{plan1.peak_bytes}")
meas1 = measured_replay(rw, feed, fetch)
assert meas1["peak_bytes"] < meas0["peak_bytes"], (
    f"MEASURED peak did not drop: {meas0['peak_bytes']} -> "
    f"{meas1['peak_bytes']}")

out = [np.asarray(a) for a in exe.run(rw, feed=feed, fetch_list=fetch)]
assert (out[0] == ref[0]).all(), "remat changed the loss bits"
assert (out[1] == ref[1]).all(), "remat changed the gradient bits"
print("remat leg ok:",
      {"est": [int(plan0.peak_bytes), int(plan1.peak_bytes)],
       "measured": [int(meas0["peak_bytes"]), int(meas1["peak_bytes"])]})
"""


def run_leg(name, code, *argv):
    with tempfile.TemporaryDirectory(prefix=f"memplan_{name}_") as d:
        env = dict(os.environ)
        env["PADDLE_FLIGHT_DIR"] = os.path.join(d, "flight")
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        args = [a.replace("@TMP@", d) for a in argv]
        p = subprocess.run(
            [sys.executable, "-c", textwrap.dedent(code), *args],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=600)
        sys.stdout.write(p.stdout)
        if p.returncode != 0:
            sys.stderr.write(p.stderr)
            print(f"memplan_gate: {name} leg FAILED", file=sys.stderr)
            return False, None
        return True, d


def main():
    with tempfile.TemporaryDirectory(prefix="memplan_doc_") as d:
        plan_json = os.path.join(d, "plan.json")
        ok, _ = run_leg("golden", GOLDEN, plan_json)
        if ok:
            # the dumped plan doc must render through trace_summary
            p = subprocess.run(
                [sys.executable, os.path.join(REPO, "tools",
                                              "trace_summary.py"),
                 "--memplan", plan_json],
                capture_output=True, text=True, cwd=REPO, timeout=120)
            sys.stdout.write(p.stdout)
            if p.returncode != 0 or "memory plan: peak" not in p.stdout:
                sys.stderr.write(p.stderr)
                print("memplan_gate: trace_summary --memplan FAILED",
                      file=sys.stderr)
                ok = False
    ok = run_leg("remat", REMAT)[0] and ok
    if not ok:
        return 1
    print("memplan_gate: all legs passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())

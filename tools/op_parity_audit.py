#!/usr/bin/env python
"""Op-parity audit: classify every forward op type the reference
registers (REGISTER_OPERATOR / REGISTER_OP_WITHOUT_GRADIENT under
``paddle/fluid/operators``) against this framework.

Classes:
  implemented — dispatched op name or public API provides the operation
  alias       — provided under a different (modern) name, mapped below
  scoped-out  — deliberately not built, with the TPU-first reason
  TODO        — real gap

Grad-op registrations (``*_grad``/``*_grad_grad``, 278 of the 581
types) are excluded: backward kernels collapse into ``jax.vjp`` by
design — every differentiable op here gets its gradient from autodiff,
checked by finite differences in tests/op_test.py.

Usage: python tools/op_parity_audit.py [--markdown OP_PARITY.md]
Reads /root/reference if present, else tools/ref_fwd_ops_snapshot.txt.
"""
from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference/paddle/fluid/operators"
SNAPSHOT = os.path.join(REPO, "tools", "ref_fwd_ops_snapshot.txt")


def reference_ops():
    if os.path.isdir(REF):
        pat = re.compile(
            r"REGISTER_OP(?:ERATOR|_WITHOUT_GRADIENT)\(\s*([a-z0-9_]+)",
            re.S)  # name may sit on the line after the open paren
        names = set()
        for root, _dirs, files in os.walk(REF):
            for fn in files:
                if not fn.endswith(".cc"):
                    continue
                with open(os.path.join(root, fn), errors="replace") as f:
                    names.update(pat.findall(f.read()))
        names = sorted(n for n in names
                       if not re.search(r"_grad(_grad)?$", n))
        with open(SNAPSHOT, "w") as f:
            f.write("\n".join(names) + "\n")
        return names
    with open(SNAPSHOT) as f:
        return [line.strip() for line in f if line.strip()]


def our_dispatched():
    out = subprocess.run(
        ["grep", "-rhoE", r'dispatch\(\s*"[a-z0-9_]+"',
         os.path.join(REPO, "paddle_tpu")],
        capture_output=True, text=True).stdout
    return {re.sub(r'.*"([a-z0-9_]+)"', r"\1", line)
            for line in out.splitlines()}


def our_api_names():
    import importlib
    import pkgutil
    import warnings
    warnings.filterwarnings("ignore")
    sys.path.insert(0, REPO)
    import paddle_tpu
    names = set()

    def walk(mod, prefix, depth=0):
        if depth > 3:
            return
        names.update(n for n in dir(mod) if not n.startswith("_"))
        for info in pkgutil.iter_modules(getattr(mod, "__path__", []),
                                         prefix + "."):
            try:
                m = importlib.import_module(info.name)
            except Exception:
                continue
            walk(m, info.name, depth + 1)

    walk(paddle_tpu, "paddle_tpu")
    return names


# ---------------------------------------------------------------------------
# Curated alias map: reference op type -> where the capability lives here.
# "alias" means the operation exists under the modern API name (often the
# same collapse paddle 2.x itself performed on these fluid-era op names).
# ---------------------------------------------------------------------------
ALIASES = {
    # CTR-stack ops implemented in ops/ctr.py (r5)
    "hash": "incubate.hash_op (host XXH64, ops/ctr.py)",
    # fluid-1.x names whose capability ships under the 2.x surface
    "cos_sim": "nn.functional.cosine_similarity",
    "margin_rank_loss": "nn.functional.margin_ranking_loss",
    "space_to_depth": "nn.functional.pixel_unshuffle / nn.PixelUnshuffle",
    "shuffle_channel": "nn.functional.channel_shuffle / nn.ChannelShuffle",
    "beam_search": "nn.BeamSearchDecoder + dynamic_decode",
    "squared_l2_distance": "paddle.sum((x-y)**2, -1, keepdim=True) — elementwise composition",
    "gaussian_random_batch_size_like": "paddle.randn(shape) — batch_size_like is shape plumbing",
    "uniform_random_batch_size_like": "paddle.uniform(shape) — batch_size_like is shape plumbing",
    # fluid-era double names: the v1/suffix-2 op is the same kernel
    "lookup_table": "nn.Embedding / nn.functional.embedding",
    "lookup_table_v2": "nn.Embedding / nn.functional.embedding",
    "reshape2": "paddle.reshape",
    "squeeze2": "paddle.squeeze",
    "unsqueeze2": "paddle.unsqueeze",
    "transpose2": "paddle.transpose",
    "flatten2": "paddle.flatten",
    "flatten": "paddle.flatten",
    "top_k": "paddle.topk",
    "top_k_v2": "paddle.topk",
    "expand": "paddle.expand",
    "expand_v2": "paddle.expand",
    "expand_as": "paddle.expand_as",
    "expand_as_v2": "paddle.expand_as",
    "matmul": "paddle.matmul",
    "matmul_v2": "paddle.matmul",
    "mul": "paddle.matmul (fluid mul == 2-D matmul with flatten)",
    "minus": "paddle.subtract",
    "sum": "paddle.add_n",
    "range": "paddle.arange",
    "crop": "paddle.crop",
    "crop_tensor": "paddle.crop",
    "pad2d": "nn.functional.pad",
    "pad3d": "nn.functional.pad",
    "pad_constant_like": "nn.functional.pad + broadcast",
    "uniform_random_inplace": "paddle.uniform / Tensor.uniform_",
    "gaussian_random": "paddle.randn / paddle.normal",
    "truncated_gaussian_random": "paddle.standard_normal + clip (initializer.TruncatedNormal)",
    "fill_any": "paddle.full / Tensor.fill_",
    "fill_zeros_like": "paddle.zeros_like",
    "fill_diagonal": "paddle.fill_diagonal / Tensor.fill_diagonal_",
    "where_index": "paddle.nonzero",
    "slogdeterminant": "paddle.linalg.slogdet",
    "determinant": "paddle.linalg.det",
    "tril_triu": "paddle.tril / paddle.triu",
    "frobenius_norm": "paddle.linalg.norm(p='fro')",
    "p_norm": "paddle.linalg.norm",
    "reduce_sum": "paddle.sum",
    "reduce_mean": "paddle.mean",
    "mean": "paddle.mean",
    "grid_sampler": "nn.functional.grid_sample",
    "max_pool2d_with_index": "nn.functional.max_pool2d(return_mask=True)",
    "max_pool3d_with_index": "nn.functional.max_pool3d(return_mask=True)",
    "softmax_with_cross_entropy": "nn.functional.cross_entropy (fused jit path)",
    "cross_entropy": "nn.functional.cross_entropy",
    "cross_entropy2": "nn.functional.cross_entropy",
    "cross_entropy_grad2": "autodiff of cross_entropy",
    "bce_loss": "nn.functional.binary_cross_entropy",
    "smooth_l1_loss": "nn.functional.smooth_l1_loss",
    "huber_loss": "nn.functional.smooth_l1_loss (delta param)",
    "kldiv_loss": "nn.functional.kl_div",
    "log_loss": "nn.functional.log_loss",
    "nll_loss": "nn.functional.nll_loss",
    "hinge_loss": "nn.functional.hinge_embedding_loss family",
    "sigmoid_focal_loss": "nn.functional.sigmoid_focal_loss (vision/ops.py)",
    "bilinear_interp": "nn.functional.interpolate(mode='bilinear')",
    "bilinear_interp_v2": "nn.functional.interpolate(mode='bilinear')",
    "nearest_interp": "nn.functional.interpolate(mode='nearest')",
    "nearest_interp_v2": "nn.functional.interpolate(mode='nearest')",
    "bicubic_interp": "nn.functional.interpolate(mode='bicubic')",
    "bicubic_interp_v2": "nn.functional.interpolate(mode='bicubic')",
    "trilinear_interp": "nn.functional.interpolate(mode='trilinear')",
    "trilinear_interp_v2": "nn.functional.interpolate(mode='trilinear')",
    "linear_interp": "nn.functional.interpolate(mode='linear')",
    "linear_interp_v2": "nn.functional.interpolate(mode='linear')",
    "elementwise_div": "paddle.divide",
    "elementwise_mul": "paddle.multiply",
    "elementwise_max": "paddle.maximum",
    "elementwise_min": "paddle.minimum",
    "elementwise_mod": "paddle.remainder",
    "elementwise_floordiv": "paddle.floor_divide",
    "elementwise_pow": "paddle.pow",
    "lrn": "nn.LocalResponseNorm",
    "scatter_nd_add": "paddle.scatter_nd_add",
    "shard_index": "paddle.shard_index",
    "cudnn_lstm": "nn.LSTM (XLA scan lowering; cudnn collapse)",
    "rnn": "nn.SimpleRNN/nn.LSTM/nn.GRU (ops/rnn.py lax.scan)",
    "lstm": "nn.LSTM",
    "lstm_unit": "nn.LSTMCell",
    "gru": "nn.GRU",
    "gru_unit": "nn.GRUCell",
    "sync_batch_norm": "nn.SyncBatchNorm (psum over dp axis)",
    "inplace_abn": "nn.BatchNorm + activation (XLA fuses; no in-place need)",
    "dropout": "nn.functional.dropout",
    "fused_softmax_mask": "softmax(x+mask) — XLA fuses; sdpa path",
    "fused_softmax_mask_upper_triangle": "causal sdpa path",
    "fused_attention": "incubate.nn.FusedMultiHeadAttention",
    "fused_feedforward": "incubate.nn.FusedFeedForward",
    "fused_embedding_eltwise_layernorm": "XLA-fused embedding+LN (inference collapse)",
    "skip_layernorm": "XLA fusion of residual+LN (fused_bias_dropout_residual_layer_norm)",
    "multihead_matmul": "scaled_dot_product_attention",
    "sparse_attention": "scaled_dot_product_attention / pallas flash (block-sparse scoped)",
    "resnet_unit": "vision resnet blocks (XLA fuses conv+bn+relu)",
    "quantize": "quantization/ fake-quant QAT ops",
    "dequantize": "quantization/ fake-quant QAT ops",
    "requantize": "quantization/ fake-quant QAT ops",
    "save": "paddle.save / static.save",
    "load": "paddle.load / static.load",
    "save_combine": "paddle.save (single-file state_dict)",
    "load_combine": "paddle.load (single-file state_dict)",
    "print": "paddle.static.Print ≡ jax.debug.print path / eager print",
    "py_func": "paddle.static.py_func (host callback)",
    "py_layer": "autograd.PyLayer",
    "run_program": "jit.TranslatedLayer / Executor program replay",
    "assign": "paddle.assign",
    "increment": "paddle.increment",
    "merge_selected_rows": "core.SelectedRows.merged()",
    "get_tensor_from_selected_rows": "core.SelectedRows.to_dense()",
    "coalesce_tensor": "XLA buffer packing (fused allreduce) — by-design",
    "squared_l2_norm": "paddle.sum(x*x) (clip path uses it fused)",
    "l1_norm": "paddle.sum(paddle.abs(x))",
    "clip_by_norm": "nn.ClipGradByNorm",
    "dgc_clip_by_norm": "fleet DGC strategy clip",
    "dgc": "fleet.DistributedStrategy dgc (gradient compression)",
    "dgc_momentum": "fleet DGC momentum path",
    "merged_momentum": "optimizer.Momentum (multi-tensor collapse: one jit)",
    "pow2_decay_with_linear_warmup": "optimizer.lr.PolynomialDecay+LinearWarmup",
    "proximal_gd": "optimizer.SGD + regularizer (proximal scoped into wd)",
    "proximal_adagrad": "optimizer.Adagrad + regularizer",
    "dpsgd": "optimizer.SGD (+noise) — DP-SGD scoped to clip+noise recipe",
    "distributed_lookup_table": "fleet.ps_layers.DistributedEmbedding",
    "pull_sparse": "fleet.ps push/pull sparse client ops",
    "pull_sparse_v2": "fleet.ps push/pull sparse client ops",
    "push_sparse": "fleet.ps push/pull sparse client ops",
    "push_sparse_v2": "fleet.ps push/pull sparse client ops",
    "send_and_recv": "fleet.ps client RPC",
    "c_allgather": "distributed.all_gather",
    "c_allreduce_sum": "distributed.all_reduce",
    "c_broadcast": "distributed.broadcast",
    "c_reduce_max": "distributed.reduce(op=MAX)",
    "c_reduce_min": "distributed.reduce(op=MIN)",
    "c_reduce_prod": "distributed.reduce(op=PROD)",
    "c_reduce_sum": "distributed.reduce(op=SUM)",
    "c_reducescatter": "distributed.reduce_scatter",
    "c_scatter": "distributed.scatter",
    "c_concat": "distributed.all_gather + concat (mp gather)",
    "c_split": "distributed.split (mp partition)",
    "c_embedding": "fleet.meta_parallel.VocabParallelEmbedding",
    "c_identity": "mp identity-with-allreduce-grad (mp_layers)",
    "alltoall": "distributed.alltoall",
    "global_scatter": "distributed.global_scatter (MoE)",
    "global_gather": "distributed.global_gather (MoE)",
    "broadcast": "distributed.broadcast",
    "allreduce": "distributed.all_reduce",
    "recv_v2": "distributed.recv / ppermute",
    "send_v2": "distributed.send / ppermute",
    "partial_send": "pp p2p slice send (spmd pipeline ppermute)",
    "partial_recv": "pp p2p slice recv (spmd pipeline ppermute)",
    "partial_concat": "pp partial gather (spmd pipeline)",
    "partial_sum": "pp partial reduce (spmd pipeline)",
    "barrier": "distributed.barrier",
    "auc": "metric.Auc",
    "chunk_eval": "metric ChunkEvaluator (text/metrics)",
    "positive_negative_pair": "metric PositiveNegativePair (ranking metric)",
    "precision_recall": "metric.Precision / metric.Recall",
    "accuracy": "metric.Accuracy",
    "linear_chain_crf": "text.crf linear-chain CRF (viterbi family)",
    "crf_decoding": "text.viterbi_decode",
    "viterbi_decode": "text.viterbi_decode",
    "warpctc": "nn.functional.ctc_loss",
    "ctc_align": "nn.functional.ctc alignment (ctc_loss family)",
    "edit_distance": "text edit_distance op",
    "unfold": "nn.functional.unfold",
    "lod_reset": "sequence segment-ids reset (ops/sequence.py)",
    "write_to_array": "static TensorArray (control-flow module)",
    "read_from_array": "static TensorArray (control-flow module)",
    "merge_lod_tensor": "sequence merge (ops/sequence.py)",
    "split_lod_tensor": "sequence split (ops/sequence.py)",
    "conditional_block": "static.nn.cond (program-capture control flow)",
    "select_input": "static.nn.cond output merge",
    "select_output": "static.nn.cond branch route",
    "gather_tree": "text.beam search gather_tree",
    "beam_search_decode": "text beam_search decode",
    # optimizer op types ≡ optimizer classes (functional kernels inside)
    "sgd": "optimizer.SGD", "momentum": "optimizer.Momentum",
    "adam": "optimizer.Adam", "adamw": "optimizer.AdamW",
    "adamax": "optimizer.Adamax", "adagrad": "optimizer.Adagrad",
    "adadelta": "optimizer.Adadelta", "rmsprop": "optimizer.RMSProp",
    "lamb": "optimizer.Lamb",
    "lars_momentum": "optimizer.Momentum(lars_coeff) / Lars",
    "sparse_momentum": "optimizer.Momentum sparse branch (SelectedRows)",
    "average_accumulates": "incubate.ModelAverage",
    # creation / fill family
    "fill": "paddle.full / Tensor.fill_",
    "fill_constant": "paddle.full",
    "fill_any_like": "paddle.full_like",
    "fill_zeros_like2": "paddle.zeros_like",
    "fill_diagonal_tensor": "paddle.fill_diagonal_tensor",
    "fill_constant_batch_size_like": "paddle.full + static shape binding",
    "assign_value": "paddle.assign",
    "uniform_random": "paddle.uniform / paddle.rand",
    "arg_max": "paddle.argmax", "arg_min": "paddle.argmin",
    "size": "paddle.numel",
    "reverse": "paddle.flip",
    "set_value": "Tensor.__setitem__ (jnp .at[].set)",
    "unique_with_counts": "paddle.unique(return_counts=True)",
    "flatten_contiguous_range": "paddle.flatten",
    "pool2d": "nn.functional.max_pool2d/avg_pool2d",
    "pool3d": "nn.functional.max_pool3d/avg_pool3d",
    "unpool": "nn.functional.max_unpool2d",
    "spp": "vision spatial-pyramid pool ≡ pool2d pyramid (deprecated layer)",
    "bilinear_tensor_product": "nn.Bilinear",
    "sigmoid_cross_entropy_with_logits":
        "nn.functional.binary_cross_entropy_with_logits",
    "hierarchical_sigmoid": "nn.functional.hsigmoid_loss",
    "random_crop": "vision.transforms.RandomCrop",
    "sampling_id": "paddle.multinomial",
    "segment_pool": "incubate segment_sum/mean/max/min",
    "depthwise_conv2d": "nn.functional.conv2d(groups=C_in)",
    "depthwise_conv2d_transpose": "nn.functional.conv2d_transpose(groups)",
    "deformable_conv": "vision.ops.deform_conv2d",
    "deformable_conv_v1": "vision.ops.deform_conv2d (mask=None == v1 without modulation)",
    "grad_add": "jax.vjp cotangent accumulation (autodiff internal)",
    "c_allreduce_max": "distributed.all_reduce(op=MAX)",
    "c_allreduce_min": "distributed.all_reduce(op=MIN)",
    "c_allreduce_prod": "distributed.all_reduce(op=PROD)",
    "partial_allgather": "pp p2p partial gather (spmd pipeline)",
    "fft_c2c": "paddle.fft.fft/ifft (complex-to-complex)",
    "fft_r2c": "paddle.fft.rfft",
    "fft_c2r": "paddle.fft.irfft",
    # runtime plumbing that exists as framework machinery here
    "feed": "Executor.run(feed=...) binding",
    "fetch": "Executor.run(fetch_list=...) binding",
    "read": "io.DataLoader iterator",
    "create_custom_reader": "io.DataLoader / reader decorators",
    "memcpy": "Tensor.to / device put (PJRT transfer)",
    "memcpy_d2h": "Tensor.cpu() / numpy()",
    "memcpy_h2d": "paddle.to_tensor (device put)",
    "share_data": "zero-copy jax array aliasing (assign)",
    "while": "static.nn.while_loop (program-capture control flow)",
    "recurrent": "jit.to_static loop -> lax.scan / static.nn.while_loop",
    "conditional_block_infer": "static.nn.cond (inference branch)",
    "tensor_array_to_tensor": "static TensorArray stack (control flow)",
    "listen_and_serv": "fleet.ps server loop (TCP)",
    "send_barrier": "fleet.ps barrier",
    "fetch_barrier": "fleet.ps barrier",
    "push_dense": "fleet.ps push_dense client op",
    "fake_init": "fleet.ps remote-param placeholder init",
    "delete_var": "Scope GC (Executor drops fetch-dead vars)",
    "get_places": "paddle.device get_available_device / jax.devices",
    "assert": "static Assert ≡ host-side enforce (errors module)",
    # quantization family -> quantization/ QAT + PTQ module
    "fake_quantize_abs_max": "quantization fake_quantize_abs_max",
    "fake_quantize_dequantize_abs_max": "quantization fake-quant (QAT)",
    "fake_quantize_range_abs_max": "quantization fake-quant (QAT)",
    "fake_quantize_moving_average_abs_max": "quantization fake-quant (QAT)",
    "fake_quantize_dequantize_moving_average_abs_max":
        "quantization fake-quant (QAT)",
    "fake_channel_wise_quantize_abs_max":
        "quantization per-channel fake-quant",
    "fake_channel_wise_quantize_dequantize_abs_max":
        "quantization per-channel fake-quant",
    "fake_channel_wise_dequantize_max_abs":
        "quantization per-channel dequant",
    "fake_dequantize_max_abs": "quantization dequant",
    "moving_average_abs_max_scale": "quantization observer (PTQ calibrate)",
    "dequantize_abs_max": "quantization dequant",
    "dequantize_log": "quantization log-scale dequant (PTQ)",
    "yolov3_loss": "vision.ops.yolo_loss",
    "ftrl": "optimizer.Ftrl",
    "decayed_adagrad": "optimizer.DecayedAdagrad",
    "faster_tokenizer": "text.FasterTokenizer",
    "multiclass_nms3": "vision.detection.multiclass_nms (rois_num output)",
    "fetch_v2": "Executor.run(fetch_list=...) binding",
}

# ---------------------------------------------------------------------------
# Scoped out, with the TPU-first reason.  These rows are deliberate:
# either vendor plumbing XLA/PJRT replaces, deprecated fluid-1.x surface
# paddle 2.x itself hides, or subsystems PARITY.md declares out of scope.
# ---------------------------------------------------------------------------
SCOPE_VENDOR = ("vendor/stream plumbing — XLA/PJRT owns scheduling & "
                "comm bootstrap (jax.distributed)")
SCOPE_FUSION_CPU = ("MKLDNN/CPU fusion-pass artifact — XLA fusion does "
                    "this automatically on TPU")
SCOPE_DEPRECATED = "deprecated fluid-1.x op, no paddle-2.x API exposes it"
SCOPE_PS_CTR = "CTR/BoxPS/SSD PS tier — scoped out per PARITY.md §7e"
SCOPE_ENGINE = "vendor inference engine — XLA is the engine here"
SCOPE_MISC = "host-side bookkeeping with no TPU analog needed"

SCOPED = {
    "c_comm_init": SCOPE_VENDOR, "c_comm_init_all": SCOPE_VENDOR,
    "c_comm_init_hccl": SCOPE_VENDOR,
    "c_comm_init_multitrainer": SCOPE_VENDOR,
    "c_gen_bkcl_id": SCOPE_VENDOR, "c_gen_hccl_id": SCOPE_VENDOR,
    "c_gen_nccl_id": SCOPE_VENDOR, "gen_bkcl_id": SCOPE_VENDOR,
    "gen_hccl_id": SCOPE_VENDOR, "gen_nccl_id": SCOPE_VENDOR,
    "c_sync_calc_stream": SCOPE_VENDOR, "c_sync_comm_stream": SCOPE_VENDOR,
    "c_wait_comm": SCOPE_VENDOR, "c_wait_compute": SCOPE_VENDOR,
    "nccl": SCOPE_VENDOR, "ascend_trigger": SCOPE_VENDOR,
    "copy_cross_scope": SCOPE_VENDOR, "marker": SCOPE_MISC,
    "nop": SCOPE_MISC, "share_buffer": SCOPE_MISC,
    "queue_generator": SCOPE_MISC, "enqueue": SCOPE_MISC,
    "dequeue": SCOPE_MISC,
    "tensorrt_engine": SCOPE_ENGINE, "lite_engine": SCOPE_ENGINE,
    "dlnne_engine": SCOPE_ENGINE,
    "fusion_group": SCOPE_FUSION_CPU, "fusion_gru": SCOPE_FUSION_CPU,
    "fusion_lstm": SCOPE_FUSION_CPU,
    "fusion_repeated_fc_relu": SCOPE_FUSION_CPU,
    "fusion_seqconv_eltadd_relu": SCOPE_FUSION_CPU,
    "fusion_seqexpand_concat_fc": SCOPE_FUSION_CPU,
    "fusion_seqpool_concat": SCOPE_FUSION_CPU,
    "fusion_seqpool_cvm_concat": SCOPE_FUSION_CPU,
    "fusion_squared_mat_sub": SCOPE_FUSION_CPU,
    "fused_embedding_fc_lstm": SCOPE_FUSION_CPU,
    "fused_embedding_seq_pool": SCOPE_FUSION_CPU,
    "attention_lstm": SCOPE_FUSION_CPU,
    "multi_gru": SCOPE_FUSION_CPU,
    "heter_listen_and_serv": "heter-PS — host-RAM embedding-tier analog in fleet/heter_ps.py",
    "pull_box_sparse": SCOPE_PS_CTR, "push_box_sparse": SCOPE_PS_CTR,
    "push_box_extended_sparse": SCOPE_PS_CTR,
    "pull_box_extended_sparse": SCOPE_PS_CTR, "push_gpups_sparse": SCOPE_PS_CTR,
    "pyramid_hash": SCOPE_PS_CTR,
    "im2sequence": SCOPE_DEPRECATED,
    "conv_shift": SCOPE_DEPRECATED,
    "fsp": SCOPE_DEPRECATED,
    "rank_loss": SCOPE_DEPRECATED,
    "bpr_loss": SCOPE_DEPRECATED,
    "center_loss": SCOPE_DEPRECATED,
    "bilateral_slice": SCOPE_DEPRECATED,
    "correlation": SCOPE_DEPRECATED,
    "tree_conv": SCOPE_DEPRECATED,
    "var_conv_2d": SCOPE_DEPRECATED,
    "row_conv": SCOPE_DEPRECATED,
    "sample_logits": SCOPE_DEPRECATED,
    "shrink_rnn_memory": SCOPE_DEPRECATED,
    "lod_tensor_to_array": SCOPE_DEPRECATED,
    "array_to_lod_tensor": SCOPE_DEPRECATED,
    "lstmp": SCOPE_DEPRECATED,
    # vendor/compiler/status plumbing
    "cinn_launch": "CINN compiler launch — XLA is the compiler here",
    "alloc_float_status": SCOPE_VENDOR,
    "clear_float_status": SCOPE_VENDOR,
    "get_float_status": SCOPE_VENDOR,
    "conv2d_fusion": SCOPE_FUSION_CPU,
    "conv2d_inception_fusion": SCOPE_FUSION_CPU,
    "fused_batch_norm_act": SCOPE_FUSION_CPU,
    "fused_bn_add_activation": SCOPE_FUSION_CPU,
    "fused_elemwise_activation": SCOPE_FUSION_CPU,
    "fused_elemwise_add_activation": SCOPE_FUSION_CPU,
    "fused_fc_elementwise_layernorm": SCOPE_FUSION_CPU,
    "fusion_transpose_flatten_concat": SCOPE_FUSION_CPU,
    # deprecated fluid-1.x surface paddle 2.x removed
    "add_position_encoding": SCOPE_DEPRECATED,
    "modified_huber_loss": SCOPE_DEPRECATED,
    "teacher_student_sigmoid_loss": SCOPE_DEPRECATED,
    "similarity_focus": SCOPE_DEPRECATED,
    "sequence_topk_avg_pooling": SCOPE_DEPRECATED,
    "match_matrix_tensor": SCOPE_DEPRECATED,
    "roi_perspective_transform": SCOPE_DEPRECATED,
    "polygon_box_transform": SCOPE_DEPRECATED,
    "prroi_pool": SCOPE_DEPRECATED + " (roi_align covers interp pooling)",
    "deformable_psroi_pooling": SCOPE_DEPRECATED,
    "lod_array_length": SCOPE_DEPRECATED + " (DynamicRNN machinery)",
    "lod_rank_table": SCOPE_DEPRECATED + " (DynamicRNN machinery)",
    "max_sequence_len": SCOPE_DEPRECATED + " (DynamicRNN machinery)",
    "reorder_lod_tensor_by_rank": SCOPE_DEPRECATED + " (DynamicRNN)",
    "rnn_memory_helper": SCOPE_DEPRECATED + " (DynamicRNN machinery)",
    "merge_lod_tensor_infer": SCOPE_DEPRECATED,
}


# name-normalization candidates for auto-matching
def candidates(op):
    yield op
    if op.endswith("_v2"):
        yield op[:-3]
    if op.endswith("2") and not op.endswith("_v2"):
        yield op[:-1]
    if op.startswith("elementwise_"):
        yield op[len("elementwise_"):]
    if op.startswith("reduce_"):
        yield op[len("reduce_"):]
    if op.startswith("c_"):
        yield op[2:]
    yield op + "_"        # inplace variants


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--markdown", default=None)
    ap.add_argument("--todo-only", action="store_true")
    args = ap.parse_args()

    ref = reference_ops()
    disp = our_dispatched()
    api = our_api_names()
    have = disp | api

    rows = []
    for op in ref:
        if op in ALIASES:
            rows.append((op, "alias", ALIASES[op]))
        elif op in SCOPED:
            rows.append((op, "scoped-out", SCOPED[op]))
        else:
            hit = next((c for c in candidates(op) if c in have), None)
            if hit is not None:
                where = "dispatch" if hit in disp else "public API"
                rows.append((op, "implemented", f"`{hit}` ({where})"))
            else:
                rows.append((op, "TODO", ""))

    counts = {}
    for _, cls, _ in rows:
        counts[cls] = counts.get(cls, 0) + 1
    total = len(rows)
    done = counts.get("implemented", 0) + counts.get("alias", 0)
    scoped = counts.get("scoped-out", 0)
    print(f"total forward op types: {total}")
    for cls in ("implemented", "alias", "scoped-out", "TODO"):
        print(f"  {cls}: {counts.get(cls, 0)}")
    print(f"implemented-or-scoped: {done + scoped} "
          f"({100.0 * (done + scoped) / total:.1f}%)")

    if args.todo_only:
        for op, cls, _ in rows:
            if cls == "TODO":
                print("TODO", op)
        return

    if args.markdown:
        with open(args.markdown, "w") as f:
            f.write(
                "# Op parity audit\n\n"
                "Every forward op type the reference registers "
                "(`REGISTER_OPERATOR`/`REGISTER_OP_WITHOUT_GRADIENT` in "
                "`paddle/fluid/operators`, grad registrations excluded — "
                "backward collapses into `jax.vjp` by design), classified "
                "against this framework.  Regenerate with "
                "`python tools/op_parity_audit.py --markdown OP_PARITY.md`."
                "\n\n"
                f"**{total} forward op types: "
                f"{counts.get('implemented', 0)} implemented, "
                f"{counts.get('alias', 0)} alias, "
                f"{scoped} scoped-out, "
                f"{counts.get('TODO', 0)} TODO — "
                f"{100.0 * (done + scoped) / total:.1f}% "
                "implemented-or-scoped.**\n\n"
                "| reference op | class | here / reason |\n|---|---|---|\n")
            for op, cls, note in rows:
                f.write(f"| `{op}` | {cls} | {note} |\n")
        print(f"wrote {args.markdown}")


if __name__ == "__main__":
    main()

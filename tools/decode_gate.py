#!/usr/bin/env python
"""CI decode gate: the continuous-batching GenerationEngine under
concurrent clients with a FIXED chaos spec must lose nothing, stream
bit-exact sequences, and compile no more executables than the bucket
count allows.

Three phases:

1. soak — 3 client threads x 4 staggered generation requests (mixed
   prompt lengths, mixed greedy/sampled configs, per-request seeds)
   under ``serve.request:fail@7`` (the 7th admission, globally, is
   injected to fail): every request must either stream to completion
   or be the single injected ChaosError; zero lost.
2. parity — every streamed sequence (iterator tokens AND final result)
   must be IDENTICAL to a sequential ``GenerationSession.generate``
   reference over the same session: continuous batching, slot
   placement, and admission timing may not change a single token.
3. accounting — total XLA compiles (``serving.compile``) <= one decode
   executable + one prefill executable per pow2 prompt bucket;
   completed + injected tallies exactly match what was submitted;
   the decode batch actually ran multi-occupancy.

Wired into tools/run_all_tests.sh next to the serving gate.
"""
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

CHAOS_SPEC = "serve.request:fail@7"
CLIENTS, PER_CLIENT = 3, 4
MAX_NEW = 5


def val(name):
    from paddle_tpu.profiler import metrics
    m = metrics.get(name)
    return m.value if m is not None else 0


def main():
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.serving.bucketing import seq_buckets
    from paddle_tpu.utils import chaos

    paddle.seed(0)
    net = GPT(GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64, ffn_mult=2))
    engine = serving.GenerationEngine(
        net, serving.GenerationEngineConfig(
            max_slots=4, max_length=64, max_new_tokens=MAX_NEW))

    rng = np.random.RandomState(7)
    jobs = []
    for c in range(CLIENTS):
        for r in range(PER_CLIENT):
            n = int(rng.randint(3, 11))
            jobs.append(dict(
                prompt=rng.randint(1, 97, (n,)).astype(np.int32),
                kw=dict(max_new_tokens=MAX_NEW,
                        do_sample=bool((c + r) % 2),
                        temperature=0.8, top_k=12, top_p=0.95,
                        seed=1000 + 10 * c + r)))

    # -- phase 1: chaos soak ------------------------------------------
    paddle.set_flags({"FLAGS_chaos_spec": CHAOS_SPEC})
    ok, injected, lost = [], [], []

    def client(tid):
        for r in range(PER_CLIENT):
            time.sleep(0.002 * (tid + r))     # staggered arrivals
            job = jobs[tid * PER_CLIENT + r]
            try:
                stream = engine.submit(job["prompt"], **job["kw"])
            except chaos.ChaosError:
                injected.append((tid, r))
                continue
            except Exception as e:            # anything else is lost
                lost.append(repr(e))
                continue
            try:
                toks = list(stream)           # the STREAMED sequence
                final = stream.result(timeout=300)
            except Exception as e:
                lost.append(repr(e))
                continue
            if toks != final.tolist():
                lost.append(f"stream/result mismatch ({tid},{r})")
            else:
                job["got"] = final
                ok.append((tid, r))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(CLIENTS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    paddle.set_flags({"FLAGS_chaos_spec": ""})

    total = CLIENTS * PER_CLIENT
    assert not lost, f"lost/wrong requests: {lost}"
    assert len(injected) == 1, \
        f"expected exactly 1 injected failure, got {len(injected)}"
    assert len(ok) == total - 1, (len(ok), total)
    assert val("chaos.injected.serve.request") == 1

    # -- phase 2: streamed == sequential reference --------------------
    for job in jobs:
        if "got" not in job:
            continue
        ref = engine.session.generate([job["prompt"]], **job["kw"])[0]
        assert np.array_equal(job["got"], ref), \
            (job["got"], ref, "continuous batching changed tokens")

    # -- phase 3: accounting ------------------------------------------
    bound = 1 + len(seq_buckets(64, engine.config.prompt_bucket_min))
    compiles = val("serving.compile")
    assert compiles <= bound, \
        f"{compiles} compiles for {total} requests (bound {bound})"
    assert val("serving.request.completed") == len(ok)
    occ = None
    from paddle_tpu.profiler import metrics as _metrics
    occ = _metrics.get("serving.decode.occupancy")
    assert occ is not None and occ._max >= 2, \
        "decode batch never ran multi-occupancy — not continuous"
    engine.close()
    print(f"decode gate OK: {len(ok)}/{total} streamed bit-exact, "
          f"1 injected chaos failure, {compiles} compiles "
          f"(bound {bound}), peak occupancy {occ._max:.0f}")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""CI fleet gate (ISSUE 13): a 2-replica serving fleet behind the
failover router must survive a dispatch-hop kill, an in-flight-bound
shed, a mid-traffic weight hot-swap, and a replica SIGKILL — with
zero lost requests, bit-exact streams, and exact counts.

Legs (one fleet, run in sequence):

0. form    — 2 replica subprocesses (GenerationEngine over a tiny GPT,
             warmup, watch_dir primed with a step-1 checkpoint of
             seed-0 weights) register TTL leases; the in-process
             router discovers both (flight ``replica.join`` == 2).
1. chaos   — ``router.dispatch:fail@3`` kills exactly one forward hop
             mid-burst: 5/5 requests complete bit-exact vs a local
             session reference, ``chaos.injected.router.dispatch`` ==
             1 and ``fleet.router.retry`` == 1, EXACTLY.
2. shed    — router pinned to max_inflight=0: 3 requests -> three
             typed 429s with ``Retry-After``; ``fleet.router.shed``
             == 3, EXACTLY; nothing reached a replica.
3. hot-swap— 4 long SSE streams run while a step-2 checkpoint
             (different weights, sha256-verified commit) lands in the
             watched directory: the router canaries ONE replica,
             passes the error-rate window on live traffic, promotes
             the other — zero dropped streams (40/40 tokens each),
             post-promote responses bit-exact vs the NEW weights,
             flight ``swap.canary`` == 1, ``swap.promote`` == 1,
             ``swap.rollback`` == 0.
4. SIGKILL — 8 concurrent requests (mixed stream/JSON) while one
             replica dies by SIGKILL: the router re-spreads (SSE
             splice for mid-stream victims) and all 8 complete
             bit-exact vs the new-weight references — zero lost;
             membership drops to 1 (flight ``replica.leave`` == 1).
5. drain   — SIGTERM to the survivor: graceful drain path exits 0.

Wired into tools/run_all_tests.sh next to the serving/decode gates.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

JOB = "fleetgate"
MAX_NEW = 8
LONG_NEW = 40
PROMPT = list(range(1, 9))

WORKER = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.serving import fleet
from paddle_tpu.models import GPT, GPTConfig

spec, rid, wdir = sys.argv[1], sys.argv[2], sys.argv[3]
paddle.seed(777)   # scrambled boot weights; the watch dir is truth
net = GPT(GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, ffn_mult=2))
eng = serving.GenerationEngine(net, serving.GenerationEngineConfig(
    max_slots=4, max_length=64, max_new_tokens={max_new}, warmup=True,
    # the SIGKILL leg funnels the WHOLE fleet's long-stream burst onto
    # one survivor: give its token-budget admission room for all of it
    # (the shed path has its own dedicated leg at the router tier)
    max_tokens_in_flight=4096))
rep = fleet.FleetReplica(
    generation_engine=eng, store=spec, job={job!r}, replica_id=rid,
    watch_dir=wdir, watch_interval=0.2, heartbeat_interval=0.2,
    lease_ttl=2.0)
rep.run()
"""


def val(name):
    from paddle_tpu.profiler import metrics
    m = metrics.get(name)
    return m.value if m is not None else 0


def net_for(seed):
    import paddle_tpu as paddle
    from paddle_tpu.models import GPT, GPTConfig
    paddle.seed(seed)
    return GPT(GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                         num_heads=2, max_seq_len=64, ffn_mult=2))


def post(url, payload, timeout=180):
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def gen_json(url, seed, max_new=MAX_NEW, prompt=PROMPT):
    body = json.load(post(url, {"prompt_ids": prompt,
                                "max_new_tokens": max_new,
                                "do_sample": True, "temperature": 0.8,
                                "top_k": 12, "seed": seed}))
    return body["tokens"]


def gen_stream(url, seed, max_new=MAX_NEW, prompt=PROMPT):
    resp = post(url, {"prompt_ids": prompt, "max_new_tokens": max_new,
                      "do_sample": True, "temperature": 0.8,
                      "top_k": 12, "seed": seed, "stream": True})
    toks, done = [], None
    for raw in resp:
        line = raw.decode().strip()
        if not line.startswith("data:"):
            continue
        d = json.loads(line[5:])
        if "token" in d:
            toks.append(d["token"])
        elif "done" in d:
            done = d
        elif "error" in d:
            raise RuntimeError(f"terminal stream error: {d}")
    assert done is not None, "stream ended without terminal event"
    assert done["tokens"] == toks, (done, toks)
    return toks


def main():
    import paddle_tpu as paddle
    from paddle_tpu.distributed import checkpoint as ckpt
    from paddle_tpu.distributed.fleet.elastic.manager import KVServer
    from paddle_tpu.generation import GenerationSession
    from paddle_tpu.profiler import flight
    from paddle_tpu.serving import fleet

    work = tempfile.mkdtemp(prefix="fleet_gate_")
    wdir = os.path.join(work, "ckpts")
    cache = os.path.join(work, "compile_cache")
    os.makedirs(wdir)

    # step 1: the fleet's boot weights (seed 0)
    p1, b1 = net_for(0).functional_state()
    ckpt.save_state(os.path.join(wdir, "1"),
                    {"params": p1, "buffers": b1}, step=1)

    kv = KVServer().start()
    spec = f"tcp://{kv.endpoint}"

    script = os.path.join(work, "replica.py")
    with open(script, "w") as f:
        f.write(WORKER.format(repo=REPO, job=JOB, max_new=MAX_NEW))
    env = dict(os.environ)
    env["FLAGS_compile_cache_dir"] = cache   # replicas share AOT blobs
    procs = [subprocess.Popen([sys.executable, script, spec,
                               f"g{i}", wdir], env=env)
             for i in (1, 2)]

    flight.clear()
    router = fleet.FleetRouter(
        spec, JOB, refresh_interval=0.1, probe_interval=0.25,
        canary_requests=2, canary_max_errors=0).start()
    url = f"http://{router.host}:{router.port}"

    try:
        # ---- leg 0: fleet formation -------------------------------------
        deadline = time.time() + 240
        while time.time() < deadline:
            if len(router._dispatchable()) == 2:
                break
            dead = [p.poll() for p in procs if p.poll() is not None]
            assert not dead, f"replica died during startup: {dead}"
            time.sleep(0.2)
        assert len(router._dispatchable()) == 2, \
            f"fleet never formed: {router.health()}"
        c = flight.counts()
        assert c.get("replica.join") == 2, c
        print(f"fleet gate: formed 2 replicas behind {url}")

        # local bit-exact references, matching the replica session
        # geometry exactly (bit-parity needs identical executables)
        ses_old = GenerationSession(net_for(0), batch_capacity=4,
                                    max_length=64, name="refold")
        prompt_arr = np.asarray(PROMPT, np.int32)

        def ref(session, seed, max_new=MAX_NEW):
            return session.generate(
                [prompt_arr], max_new_tokens=max_new, do_sample=True,
                temperature=0.8, top_k=12, seed=seed)[0].tolist()

        # ---- leg 1: dispatch-hop chaos, exact counts --------------------
        paddle.set_flags(
            {"FLAGS_chaos_spec": "router.dispatch:fail@3"})
        try:
            got = [gen_json(url, seed=100 + i) for i in range(5)]
        finally:
            paddle.set_flags({"FLAGS_chaos_spec": ""})
        for i, toks in enumerate(got):
            expect = ref(ses_old, 100 + i)
            assert toks == expect, \
                (f"chaos leg request {i}: {toks} != {expect}")
        inj = val("chaos.injected.router.dispatch")
        retries = val("fleet.router.retry")
        assert inj == 1, f"expected exactly 1 injected hop kill: {inj}"
        assert retries == 1, f"expected exactly 1 failover retry: " \
            f"{retries}"
        print("fleet gate: chaos leg OK — 5/5 bit-exact, 1 injected "
              "hop kill, 1 failover retry")

        # ---- leg 2: typed shed at the in-flight bound -------------------
        old_inflight = router.max_inflight
        router.max_inflight = 0
        shed_before = val("fleet.router.shed")
        for _ in range(3):
            try:
                post(url, {"prompt_ids": PROMPT})
                raise AssertionError("overloaded router answered 200")
            except urllib.error.HTTPError as e:
                assert e.code == 429, e.code
                assert e.headers.get("Retry-After"), "no Retry-After"
                assert json.loads(e.read().decode())["reason"] == \
                    "router_overload"
        router.max_inflight = old_inflight
        assert val("fleet.router.shed") == shed_before + 3
        print("fleet gate: shed leg OK — 3 typed 429s with "
              "Retry-After, exact count")

        # ---- leg 3: mid-traffic hot-swap (canary -> promote) ------------
        streams = {}

        def long_stream(seed):
            streams[seed] = gen_stream(url, seed, max_new=LONG_NEW)

        threads = [threading.Thread(target=long_stream, args=(s,))
                   for s in (201, 202, 203, 204)]
        for t in threads:
            t.start()
        time.sleep(0.3)        # streams are live mid-generation
        p2, b2 = net_for(1).functional_state()
        ckpt.save_state(os.path.join(wdir, "2"),
                        {"params": p2, "buffers": b2}, step=2)
        deadline = time.time() + 90
        seed = 300
        while time.time() < deadline:
            if flight.counts().get("swap.promote"):
                break
            gen_json(url, seed=seed)   # traffic feeds the canary window
            seed += 1
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=180)
        c = flight.counts()
        assert c.get("swap.canary") == 1, c
        assert c.get("swap.promote") == 1, c
        assert not c.get("swap.rollback"), c
        # zero dropped streams across the swap: every long stream got
        # its full budget (a stream spanning the swap mixes old/new
        # tokens — completeness, not token values, is its contract)
        assert sorted(streams) == [201, 202, 203, 204]
        for s, toks in streams.items():
            assert len(toks) == LONG_NEW, \
                f"stream {s} dropped tokens: {len(toks)}/{LONG_NEW}"
        # served bytes flipped: post-promote traffic is the NEW weights
        ses_new = GenerationSession(net_for(1), batch_capacity=4,
                                    max_length=64, name="refnew")
        post_swap = gen_json(url, seed=999)
        expect = ref(ses_new, 999)
        assert post_swap == expect, (post_swap, expect)
        old_expect = ref(ses_old, 999)
        assert post_swap != old_expect, \
            "post-swap tokens still match the OLD weights"
        assert router.health()["current_step"] == 2
        print("fleet gate: hot-swap leg OK — canary promoted, 4/4 "
              f"streams x {LONG_NEW} tokens across the swap, served "
              "bytes flipped to step 2")

        # ---- leg 4: SIGKILL one replica mid-traffic ---------------------
        results, errors = {}, []

        def client(i):
            try:
                seed = 400 + i
                if i % 2:
                    results[i] = gen_stream(url, seed,
                                            max_new=LONG_NEW)
                else:
                    results[i] = gen_json(url, seed,
                                          max_new=LONG_NEW)
            except Exception as e:   # noqa: BLE001 — a loss is a failure
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.3)            # requests are in flight
        procs[0].send_signal(signal.SIGKILL)
        for t in threads:
            t.join(timeout=240)
        assert not errors, f"lost requests after SIGKILL: {errors}"
        assert sorted(results) == list(range(8))
        for i, toks in results.items():
            expect = ref(ses_new, 400 + i, max_new=LONG_NEW)
            assert toks == expect, \
                (f"request {i} not bit-exact after failover: "
                 f"{toks} != {expect}")
        # membership converges to the survivor; the victim leaves ONCE
        deadline = time.time() + 15
        while time.time() < deadline:
            if len(router._replicas) == 1:
                break
            time.sleep(0.1)
        assert set(router._replicas) == {"g2"}, router.health()
        c = flight.counts()
        assert c.get("replica.leave") == 1, c
        assert c.get("replica.join") == 2, c
        print("fleet gate: SIGKILL leg OK — 8/8 requests bit-exact "
              "through failover, membership 2 -> 1, exact "
              "join/leave counts")

        # ---- leg 5: graceful drain of the survivor ----------------------
        procs[1].send_signal(signal.SIGTERM)
        rc = procs[1].wait(timeout=60)
        assert rc == 0, f"survivor drain exited {rc}"
        print("fleet gate: drain leg OK — survivor exited 0 after "
              "graceful drain")

        print("fleet gate OK: chaos failover, typed shed, "
              "canary-promoted hot-swap, SIGKILL re-spread, drain — "
              "all exact")
    finally:
        router.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        kv.stop()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""CI SLO gate: multi-tenant serving (PR 18) under a saturated block
pool must keep interactive traffic fast by preempting batch decodes to
host memory — and the preempted streams must come back bit-identical.

Scenario: a 3-block pool (the whole KV budget of one long request) is
held by a long batch-priority stream while interactive requests that
need the entire pool burst in.  Each burst must preempt the batch
victim to pinned host memory, run, and hand the pool back; the batch
stream resumes where it left off.  Across M batch streams x K bursts
each the gate asserts:

1. zero lost requests — every batch stream and every interactive burst
   completes; no typed shed, no exception, no stream/result mismatch;
2. exact preemption accounting — ``serve.preempt`` and
   ``serve.resume`` flight counts, the ``request.preempted`` /
   ``request.resumed`` engine counters and the per-tenant
   ``tenant.bulk.preempted`` counter all equal M*K exactly (one park
   and one resume per burst, never a double-preempt);
3. bit-exact resume — each batch stream (greedy AND two sampled
   configs) equals its unpreempted single-engine reference token for
   token: parking KV to host and rebuilding the block table may not
   change a single token;
4. interactive SLO — burst p99 (which includes the preemption swap)
   stays under a CI-safe bound while the batch streams are still live;
5. clean drain — the pool returns to all-free after close (no leaked
   refcounts in either the parked or resumed path).

Wired into tools/run_all_tests.sh next to the paged and memplan gates.
"""
import math
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

M, K = 3, 2                  # batch streams x interactive bursts each
MAX_NEW_A = 30               # batch stream length (8 + 30 = 3 blocks)
MAX_NEW_B = 4                # interactive burst length
BS = 16                      # block size; pool = 3 blocks = 48 tokens
P99_BOUND_S = 60.0           # CI-safe: catches starvation, not noise


def val(name):
    from paddle_tpu.profiler import metrics
    m = metrics.get(name)
    return m.value if m is not None else 0


def wait_until(pred, timeout=60.0, what="condition"):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.005)


def main():
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.profiler import flight

    paddle.seed(0)
    net = GPT(GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=64, ffn_mult=2))

    def engine(name):
        return serving.PagedGenerationEngine(
            net, serving.GenerationEngineConfig(
                max_slots=2, max_length=64, max_new_tokens=MAX_NEW_A,
                block_size=BS, num_blocks=3, prefix_cache_blocks=0,
                warmup="off", name=name))

    # greedy + two sampled configs: resume must be bit-exact for all
    jobs = []
    for j, kw in enumerate((
            dict(do_sample=False, seed=7),
            dict(do_sample=True, temperature=0.9, top_k=0, top_p=1.0,
                 seed=11),
            dict(do_sample=True, temperature=0.8, top_k=12, top_p=0.95,
                 seed=13))):
        jobs.append(dict(
            prompt=np.arange(1 + j, 9 + j, dtype=np.int32),
            kw=dict(max_new_tokens=MAX_NEW_A, **kw)))
    pB = np.arange(1, 41, dtype=np.int32)     # prefill needs all 3

    # -- unpreempted references (each stream alone on a fresh pool) ---
    ref_eng = engine("slo_ref")
    try:
        for job in jobs:
            job["ref"] = ref_eng.generate(job["prompt"], timeout=300,
                                          **job["kw"])
    finally:
        ref_eng.close()

    # -- saturated pool + interactive bursts --------------------------
    flight.clear()
    eng = engine("slo_gate")
    lat, resumes = [], 0
    try:
        for job in jobs:
            sA = eng.submit(job["prompt"], tenant="bulk",
                            priority="batch", **job["kw"])
            it = iter(sA)
            head = [next(it), next(it)]   # victim is live, mid-decode
            for _ in range(K):
                t0 = time.monotonic()
                outB = eng.submit(
                    pB, max_new_tokens=MAX_NEW_B, tenant="live",
                    priority="interactive").result(timeout=300)
                lat.append(time.monotonic() - t0)
                assert len(outB) == MAX_NEW_B, "lost interactive tokens"
                resumes += 1              # victim must be back in a
                wait_until(               # slot before the next burst
                    lambda: val("slo_gate.request.resumed") >= resumes,
                    what=f"resume #{resumes}")
                head.append(next(it))
            tail = list(it)               # one seamless SSE stream
            got = np.asarray(head + tail, np.int32)
            assert np.array_equal(got, job["ref"]), \
                (got, job["ref"], "preempt/resume changed tokens")
    finally:
        eng.close()

    # -- zero lost + exact preemption accounting ----------------------
    c = flight.counts()
    assert c.get("serve.preempt", 0) == M * K, c
    assert c.get("serve.resume", 0) == M * K, c
    assert val("slo_gate.request.preempted") == M * K
    assert val("slo_gate.request.resumed") == M * K
    assert val("slo_gate.request.completed") == M + M * K
    for reason in ("rejected", "shed_deadline", "shed_kv_blocks",
                   "shed_deadline_preempted"):
        assert val(f"slo_gate.request.{reason}") == 0, reason
    assert val("slo_gate.tenant.bulk.preempted") == M * K
    assert val("slo_gate.tenant.bulk.completed") == M
    assert val("slo_gate.tenant.live.completed") == M * K
    assert eng.pool.available == eng.pool.num_blocks, \
        "leaked KV blocks after preempt/resume workload + close"

    # -- interactive p99 under batch saturation -----------------------
    p99 = sorted(lat)[max(0, math.ceil(0.99 * len(lat)) - 1)]
    assert p99 < P99_BOUND_S, \
        f"interactive p99 {p99:.2f}s breaches {P99_BOUND_S}s bound"

    print(f"slo gate OK: {M} batch streams bit-exact across {M * K} "
          f"preempt->resume cycles ({c.get('serve.preempt', 0)} parks "
          f"to host, {c.get('serve.resume', 0)} resumes, exact), "
          f"{M * K} interactive bursts p99 {p99 * 1e3:.0f}ms "
          f"(bound {P99_BOUND_S:.0f}s), zero lost, pool drained "
          f"to all-free")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""CI disagg gate (ISSUE 19): a 1-prefill + 2-decode fleet behind the
prefix-aware router must serve a shared-prompt workload bit-exact vs a
monolithic reference, keep the fleet-wide prefix hit rate at the
single-replica level, and ride out an injected KV-transfer failure —
with zero lost requests and every pool drained to all-free.

Legs (one fleet, run in sequence):

0. form      — 3 replica subprocesses (paged engines over one seed-0
               GPT): ``pre`` (role=prefill), ``d1``/``d2``
               (role=decode, d2 armed with ``kv.transfer:fail@1``).
               The router discovers all 3 but dispatches to exactly
               the 2 decode replicas (prefill is filtered).
1. chaos     — the first cold request lands DIRECTLY on d2: its one
               chain pull from ``pre`` dies by injection
               (``chaos.injected.kv.transfer`` == 1,
               ``kv.transfer.fail`` >= 1 on d2) and the request
               completes bit-exact anyway via local re-prefill —
               a transfer failure costs latency, never a token.
2. traffic   — 2 shared 24-token heads x 3 suffix variants x
               (greedy + 2 sampled configs), JSON and SSE, through
               the router: every stream bit-exact vs a monolithic
               PagedGenerationEngine reference with identical
               geometry; chains actually flow (``kv.transfer.fetch``
               >= 1 on d1) and at least one dispatch is steered by a
               published prefix head (``fleet.router.prefix_routed``).
3. hit rate  — fleet-wide prefix-cache hit rate (both decode
               replicas, probe lookups included) within 0.15 of the
               monolith's rate on the same workload (the ROADMAP
               "fleet hit rate ~= single-replica rate" gate).
4. drain     — SIGTERM all replicas: graceful drain, every worker
               asserts its pool returned to all-free (exit 3 on a
               leaked block) and exits 0.

Wired into tools/run_all_tests.sh next to the fleet and slo gates.
"""
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

JOB = "disagggate"
MAX_NEW = 8
BS = 8
HEAD_A = list(range(1, 25))          # 24 tokens = 3 full blocks
HEAD_B = list(range(30, 54))
SUFFIXES = [[60, 61, 62, 63], [70, 71, 72, 73], [80, 81, 82, 83]]
CONFIGS = [dict(do_sample=False, seed=7),
           dict(do_sample=True, temperature=0.9, top_k=0, top_p=1.0,
                seed=11),
           dict(do_sample=True, temperature=0.8, top_k=12, top_p=0.95,
                seed=13)]

WORKER = """
import os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.serving import fleet
from paddle_tpu.models import GPT, GPTConfig

spec, rid, role = sys.argv[1], sys.argv[2], sys.argv[3]
paddle.seed(0)          # every replica serves identical weights
net = GPT(GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, ffn_mult=2))
eng = serving.PagedGenerationEngine(net, serving.GenerationEngineConfig(
    max_slots=2, max_length=64, max_new_tokens={max_new},
    block_size={bs}, num_blocks=32, prefix_cache_blocks=16,
    warmup="off", name=rid))
rep = fleet.FleetReplica(
    generation_engine=eng, store=spec, job={job!r}, replica_id=rid,
    role=role, heartbeat_interval=0.2, lease_ttl=2.0)
rep.run()
if eng.pool.available != eng.pool.num_blocks:
    print("POOL LEAK:", eng.pool.available, "/", eng.pool.num_blocks,
          file=sys.stderr)
    sys.exit(3)
"""


def val(name):
    from paddle_tpu.profiler import metrics
    m = metrics.get(name)
    return m.value if m is not None else 0


def post(url, payload, timeout=300):
    req = urllib.request.Request(
        url + "/v1/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


def gen_json(url, prompt, kw):
    return json.load(post(url, dict(prompt_ids=prompt,
                                    max_new_tokens=MAX_NEW,
                                    **kw)))["tokens"]


def gen_stream(url, prompt, kw):
    resp = post(url, dict(prompt_ids=prompt, max_new_tokens=MAX_NEW,
                          stream=True, **kw))
    toks, done = [], None
    for raw in resp:
        line = raw.decode().strip()
        if not line.startswith("data:"):
            continue
        d = json.loads(line[5:])
        if "token" in d:
            toks.append(d["token"])
        elif "done" in d:
            done = d
        elif "error" in d:
            raise RuntimeError(f"terminal stream error: {d}")
    assert done is not None, "stream ended without terminal event"
    assert done["tokens"] == toks, (done, toks)
    return toks


def scrape(url):
    """Prometheus text -> {name: float} (dots exported as _)."""
    with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
        text = r.read().decode()
    out = {}
    for line in text.splitlines():
        if line.startswith("#") or "{" in line:
            continue
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                pass
    return out


def main():
    from paddle_tpu import serving
    from paddle_tpu.distributed.fleet.elastic.manager import KVServer
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.serving import fleet
    import paddle_tpu as paddle

    work = tempfile.mkdtemp(prefix="disagg_gate_")
    cache = os.path.join(work, "compile_cache")
    kv = KVServer().start()
    spec = f"tcp://{kv.endpoint}"

    script = os.path.join(work, "replica.py")
    with open(script, "w") as f:
        f.write(WORKER.format(repo=REPO, job=JOB, max_new=MAX_NEW,
                              bs=BS))
    env = dict(os.environ)
    env["FLAGS_compile_cache_dir"] = cache   # replicas share AOT blobs
    env_chaos = dict(env, FLAGS_chaos_spec="kv.transfer:fail@1")
    procs = [
        subprocess.Popen([sys.executable, script, spec, "pre",
                          "prefill"], env=env),
        subprocess.Popen([sys.executable, script, spec, "d1",
                          "decode"], env=env),
        subprocess.Popen([sys.executable, script, spec, "d2",
                          "decode"], env=env_chaos),
    ]

    paddle.set_flags({"FLAGS_compile_cache_dir": cache})
    router = fleet.FleetRouter(spec, JOB, refresh_interval=0.1,
                               probe_interval=0.25,
                               manage_swaps=False).start()
    url = f"http://{router.host}:{router.port}"

    jobs = []           # (prompt, config, use_stream)
    for head in (HEAD_A, HEAD_B):
        for sfx in SUFFIXES:
            for j, kw in enumerate(CONFIGS):
                jobs.append((head + sfx, kw, j == 1))

    try:
        # ---- leg 0: formation — 3 known, 2 dispatchable -----------------
        deadline = time.time() + 240
        while time.time() < deadline:
            if len(router._replicas) == 3 \
                    and len(router._dispatchable()) == 2:
                break
            dead = [p.poll() for p in procs if p.poll() is not None]
            assert not dead, f"replica died during startup: {dead}"
            time.sleep(0.2)
        cands = {i.replica_id for i in router._dispatchable()}
        assert cands == {"d1", "d2"}, \
            f"prefill must be filtered from dispatch: {cands}"
        eps = {rid: i.endpoint
               for rid, i in router._replicas.items()}
        print(f"disagg gate: formed pre+d1+d2 behind {url} "
              "(prefill filtered from dispatch)")

        # monolithic reference: identical geometry on one engine
        paddle.seed(0)
        net = GPT(GPTConfig(vocab_size=97, hidden_size=32,
                            num_layers=2, num_heads=2, max_seq_len=64,
                            ffn_mult=2))
        mono = serving.PagedGenerationEngine(
            net, serving.GenerationEngineConfig(
                max_slots=2, max_length=64, max_new_tokens=MAX_NEW,
                block_size=BS, num_blocks=32, prefix_cache_blocks=16,
                warmup="off", name="dgmono"))
        refs = []
        for prompt, kw, _s in jobs:
            refs.append(mono.generate(
                np.asarray(prompt, np.int32), timeout=300,
                max_new_tokens=MAX_NEW, **kw).tolist())

        # ---- leg 1: injected transfer failure, ridden out ---------------
        # d2's FIRST chain pull dies by chaos: the request must still
        # complete bit-exact via local re-prefill (hit d2 directly so
        # the injection deterministically lands there)
        i_b0 = next(i for i, (p, kw, _s) in enumerate(jobs)
                    if p[:24] == HEAD_B and kw is CONFIGS[0])
        d2url = f"http://{eps['d2']}"
        got = gen_json(d2url, jobs[i_b0][0], jobs[i_b0][1])
        assert got == refs[i_b0], \
            f"chaos-leg stream not bit-exact: {got} != {refs[i_b0]}"
        m2 = scrape(d2url)
        assert m2.get("chaos_injected_kv_transfer") == 1, m2.get(
            "chaos_injected_kv_transfer")
        assert m2.get("kv_transfer_fail", 0) >= 1
        done = {i_b0}
        print("disagg gate: chaos leg OK — 1 injected kv.transfer "
              "kill, request bit-exact via local re-prefill, "
              "zero lost")

        # ---- leg 2: shared-prompt traffic through the router ------------
        first_a = next(i for i, (p, _kw, _s) in enumerate(jobs)
                       if p[:24] == HEAD_A)
        got = gen_json(url, jobs[first_a][0], jobs[first_a][1])
        assert got == refs[first_a]
        done.add(first_a)
        # wait for a decode replica to advertise the head it now holds
        deadline = time.time() + 30
        while time.time() < deadline:
            if any(i.prefix_heads for i in router._replicas.values()):
                break
            time.sleep(0.1)
        assert any(i.prefix_heads for i in router._replicas.values()), \
            "no replica ever published prefix_heads"
        for i, (prompt, kw, use_stream) in enumerate(jobs):
            if i in done:
                continue
            fn = gen_stream if use_stream else gen_json
            got = fn(url, prompt, kw)
            assert got == refs[i], \
                (f"request {i} not bit-exact: {got} != {refs[i]} "
                 f"({kw})")
        m1 = scrape(f"http://{eps['d1']}")
        assert m1.get("kv_transfer_fetch", 0) >= 1, \
            "d1 never adopted a chain from the prefill replica"
        assert val("fleet.router.prefix_routed") >= 1, \
            "no dispatch was ever steered by a published prefix head"
        print(f"disagg gate: traffic leg OK — {len(jobs)}/{len(jobs)} "
              "streams bit-exact vs the monolith (greedy + sampled), "
              f"{int(m1.get('kv_transfer_fetch', 0))} chains adopted "
              f"on d1, {int(val('fleet.router.prefix_routed'))} "
              "prefix-steered dispatches")

        # ---- leg 3: fleet hit rate ~= single-replica rate ---------------
        m2 = scrape(d2url)
        fleet_hit = m1.get("d1_prefix_cache_hit", 0) \
            + m2.get("d2_prefix_cache_hit", 0)
        fleet_miss = m1.get("d1_prefix_cache_miss", 0) \
            + m2.get("d2_prefix_cache_miss", 0)
        fleet_rate = fleet_hit / max(1.0, fleet_hit + fleet_miss)
        mono_hit = val("dgmono.prefix_cache.hit")
        mono_miss = val("dgmono.prefix_cache.miss")
        mono_rate = mono_hit / max(1.0, mono_hit + mono_miss)
        assert fleet_rate + 0.15 >= mono_rate, \
            (f"fleet prefix hit rate {fleet_rate:.3f} fell behind the "
             f"single-replica rate {mono_rate:.3f}")
        mono.close()
        assert mono.pool.available == mono.pool.num_blocks
        print(f"disagg gate: hit-rate leg OK — fleet {fleet_rate:.3f} "
              f"vs single-replica {mono_rate:.3f}")

        # ---- leg 4: graceful drain, pools all-free ----------------------
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for i, p in enumerate(procs):
            rc = p.wait(timeout=60)
            assert rc == 0, \
                f"replica {i} drain exited {rc} (3 = leaked KV blocks)"
        print("disagg gate OK: prefill/decode split bit-exact vs the "
              "monolith, fleet hit rate held, injected transfer "
              "failure ridden out with zero lost requests, all pools "
              "drained to all-free")
    finally:
        router.stop()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
        kv.stop()
        shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    main()

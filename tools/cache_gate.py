#!/usr/bin/env python
"""CI gate: the AOT artifact store drives second-process cold starts to
ZERO fresh XLA compiles.

Runs the same workload twice in two fresh processes sharing one
``FLAGS_compile_cache_dir``:

    1. a seeded ``Model.fit`` (the hapi jitted train step),
    2. a serving-engine load (``InferenceEngine`` with
       ``EngineConfig(warmup=True)`` over a saved artifact) + one
       request,
    3. a ``GenerationSession`` prefill/decode generate call.

Asserted contract:

- run 1 misses and stores artifacts (the store actually engaged);
- run 2 performs **zero** fresh XLA compiles: every AOT site hits the
  artifact store (``aot_store.miss == 0``, hits == run 1's misses) AND
  jax's persistent compilation cache gains **zero** new entries (so
  nothing compiled outside the store's sight either);
- run 2 is **bit-exact** with run 1: same final fit loss + parameter
  bytes, same served outputs, same generated tokens;
- run 2's cold start (fit wall time to first step) is no slower than
  2x run 1's — deserialization must actually be cheaper than
  compilation (generous bound: CI machines are noisy).

Usage: python tools/cache_gate.py          (parent: orchestrates)
       python tools/cache_gate.py --child  (one measured run)
"""
import hashlib
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def child(workdir: str):
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.io as io
    from paddle_tpu import serving
    from paddle_tpu.jit import InputSpec
    from paddle_tpu.models import GPT, GPTConfig
    from paddle_tpu.generation import GenerationSession
    from paddle_tpu.utils import artifact_store as aot
    from paddle_tpu.utils import compile_cache as cc

    assert aot.active() is not None, \
        "artifact store not armed (FLAGS_compile_cache_dir unset?)"
    jax_entries0 = cc.entry_count()
    out = {}

    # -- leg 1: seeded fit ---------------------------------------------
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    model = paddle.Model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    model.prepare(opt, paddle.nn.MSELoss())
    rng = np.random.RandomState(0)
    x = rng.rand(64, 8).astype("float32")
    y = rng.rand(64, 1).astype("float32")
    samples = [(x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8])
               for i in range(8)]

    class DS(io.Dataset):
        def __len__(self):
            return len(samples)

        def __getitem__(self, i):
            return samples[i]

    loader = io.DataLoader(DS(), batch_size=None, shuffle=False)
    t0 = time.perf_counter()
    model.fit(loader, epochs=1, verbose=0)
    out["fit_s"] = round(time.perf_counter() - t0, 3)
    out["fit_loss"] = repr(float(
        model.train_batch([x[:8]], [y[:8]])["loss"]))
    h = hashlib.sha256()
    for p in net.parameters():
        h.update(np.asarray(p._data).tobytes())
    out["fit_params_sha"] = h.hexdigest()

    # -- leg 2: serving-engine load + one request ----------------------
    paddle.seed(1)
    prefix = os.path.join(workdir, "model", "m")
    if not os.path.exists(prefix + ".pdmodel"):
        os.makedirs(os.path.dirname(prefix), exist_ok=True)
        snet = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                             nn.Linear(16, 8))
        paddle.jit.save(snet, prefix, input_spec=[
            InputSpec([-1, 8], "float32", name="x")])
    engine = serving.InferenceEngine(prefix, serving.EngineConfig(
        max_batch_size=8, num_workers=1, warmup=True))
    out["warmed_buckets"] = engine.warmed_buckets
    served = engine.infer(
        [np.linspace(0, 1, 3 * 8).reshape(3, 8).astype("float32")],
        timeout=120)
    engine.close()
    out["serve_sha"] = hashlib.sha256(
        b"".join(np.ascontiguousarray(o).tobytes()
                 for o in served)).hexdigest()

    # -- leg 3: generation session -------------------------------------
    paddle.seed(2)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=32, ffn_mult=2)
    gpt = GPT(cfg)
    sess = GenerationSession(gpt, batch_capacity=2, max_length=32)
    toks = sess.generate(
        [np.arange(1, 6, dtype=np.int32),
         np.arange(3, 12, dtype=np.int32)],
        max_new_tokens=8, do_sample=True, temperature=0.9,
        seeds=[11, 22])
    out["gen_tokens"] = [t.tolist() for t in toks]

    out["aot"] = aot.stats()
    out["jax_cache_new_entries"] = cc.entry_count() - jax_entries0
    print("CACHE_GATE_JSON " + json.dumps(out))


def run_child(workdir: str, cache_dir: str) -> dict:
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               FLAGS_compile_cache_dir=cache_dir,
               FLAGS_prefetch_to_device="2",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", workdir],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO)
    if r.returncode != 0:
        print(r.stdout)
        print(r.stderr, file=sys.stderr)
        raise SystemExit(f"cache_gate child failed (rc={r.returncode})")
    for line in r.stdout.splitlines():
        if line.startswith("CACHE_GATE_JSON "):
            return json.loads(line[len("CACHE_GATE_JSON "):])
    print(r.stdout)
    print(r.stderr, file=sys.stderr)
    raise SystemExit("cache_gate child emitted no JSON")


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child(sys.argv[2])
        return
    base = tempfile.mkdtemp(prefix="paddle_cache_gate_")
    cache_dir = os.path.join(base, "compile_cache")
    r1 = run_child(base, cache_dir)
    r2 = run_child(base, cache_dir)
    print(f"[cache_gate] run1 aot={r1['aot']} "
          f"jax_new={r1['jax_cache_new_entries']} fit={r1['fit_s']}s")
    print(f"[cache_gate] run2 aot={r2['aot']} "
          f"jax_new={r2['jax_cache_new_entries']} fit={r2['fit_s']}s")

    # the store engaged on run 1
    assert r1["aot"]["miss"] > 0 and \
        r1["aot"]["store"] == r1["aot"]["miss"], \
        f"run 1 did not populate the artifact store: {r1['aot']}"
    assert r1["aot"]["hit"] == 0, \
        f"run 1 hit a supposedly-fresh store: {r1['aot']}"
    # run 2: zero fresh XLA compiles, everything from the store
    assert r2["aot"]["miss"] == 0 and r2["aot"]["corrupt"] == 0, \
        f"run 2 paid fresh AOT compiles: {r2['aot']}"
    assert r2["aot"]["hit"] == r1["aot"]["miss"], \
        (f"run 2 hits {r2['aot']['hit']} != run 1 misses "
         f"{r1['aot']['miss']} — an AOT site changed its fingerprint "
         "across identical processes")
    assert r2["jax_cache_new_entries"] == 0, \
        (f"run 2 compiled {r2['jax_cache_new_entries']} program(s) "
         "outside the artifact store (persistent-cache entries grew)")
    # bit-exactness across processes
    for k in ("fit_loss", "fit_params_sha", "serve_sha", "gen_tokens"):
        assert r1[k] == r2[k], \
            f"run 2 not bit-exact with run 1 on {k}: {r1[k]} vs {r2[k]}"
    assert r2["warmed_buckets"] == r1["warmed_buckets"] > 0
    # deserialization must actually beat compilation: generous 2x +
    # 1s slack absorbs 1-core CI noise while still catching a
    # pathologically slow store (run 1's fit includes every compile)
    assert r2["fit_s"] <= 2.0 * r1["fit_s"] + 1.0, \
        (f"warm fit ({r2['fit_s']}s) slower than 2x the cold fit "
         f"({r1['fit_s']}s) — artifact loads cost more than compiles?")
    print("[cache_gate] OK: second-process run performed 0 fresh XLA "
          f"compiles ({r2['aot']['hit']} artifact hits), bit-exact "
          "across fit + engine load + generation")


if __name__ == "__main__":
    main()

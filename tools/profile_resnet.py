"""Op-level profile of the ResNet train step (the bench.py resnet leg).

Default mode drives the PR 1 host tracer through an instrumented EAGER
train step and prints the per-op time table plus the conv/norm/
elementwise/optimizer phase shares — the same ``tracer.op_table()`` /
``tracer.phase_shares()`` path bench.py's MFU breakdown reads, so the
two can never disagree on methodology.  ``--xprof`` keeps the original
device-side capture (jax.profiler trace + hlo_stats via
tools/profile_bert.py) for runs on real hardware.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build(depth: int, batch: int, hw: int, nclass: int, amp):
    import paddle_tpu as paddle

    paddle.seed(0)
    factory = getattr(paddle.vision.models, f"resnet{depth}")
    net = factory(num_classes=nclass)
    model = paddle.Model(net)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(), amp_configs=amp)
    rng = np.random.RandomState(0)
    x = np.asarray(rng.rand(batch, 3, hw, hw), np.float32)
    y = np.asarray(rng.randint(0, nclass, (batch, 1)), np.int32)
    return net, model, opt, x, y


def op_profile(args):
    """Host-tracer path: eager steps so every op goes through
    core.dispatch and lands in the tracer's op table."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.profiler import tracer

    net, model, opt, x, y = build(args.depth, args.batch, args.hw,
                                  args.nclass, None)
    p0 = next(iter(net.parameters()))
    # shared recipe with bench.py's phase_shares leg (warm eager caches
    # outside the window, instrumented eager steps, optimizer wall time
    # as its own bucket) — one implementation, no methodology drift
    table, shares, wall = tracer.eager_phase_profile(
        model, opt, x, y, p0, steps=args.steps)

    rows = sorted(table.items(), key=lambda kv: -kv[1]["total_ns"])
    print(f"\n== op table ({args.steps} eager steps, "
          f"{wall:.2f}s wall, resnet{args.depth} b{args.batch} "
          f"{args.hw}x{args.hw}) ==")
    print(f"{'op':<34}{'phase':<12}{'calls':>7}{'total ms':>10}"
          f"{'avg us':>9}")
    for op, s in rows[:args.top]:
        print(f"{op:<34}{s['phase']:<12}{s['calls']:>7}"
              f"{s['total_ns'] / 1e6:>10.2f}"
              f"{s['avg_ns'] / 1e3:>9.1f}")
    print("\n== phase shares ==")
    for phase, s in shares.items():
        # synthetic buckets (optimizer wall time) carry calls=None —
        # they run as fused jit calls, not per-op dispatches
        calls = "—" if s["calls"] is None else f"{s['calls']} dispatches"
        print(f"{phase:<14}{s['time_frac'] * 100:>6.1f}%  "
              f"({s['total_ns'] / 1e6:.1f} ms, {calls})")
    return shares


def xprof_capture(args):
    """Original device-side capture (TPU runs): jax.profiler trace +
    hlo_stats summary via tools/profile_bert.py."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle

    net, model, opt, x, y = build(args.depth, args.batch, args.hw,
                                  args.nclass, "O2")
    xj = jnp.asarray(x)
    yj = jnp.asarray(y, jnp.int32)
    model.train_batch([xj], [yj])
    p0 = next(iter(net.parameters()))
    jax.block_until_ready(p0._data)
    os.makedirs(args.logdir, exist_ok=True)
    with jax.profiler.trace(args.logdir):
        t0 = time.perf_counter()
        for _ in range(args.steps):
            model.train_batch([xj], [yj])
        jax.block_until_ready(p0._data)
        dt = time.perf_counter() - t0
    print(f"[capture] {args.steps} steps in {dt:.3f}s -> "
          f"{args.batch * args.steps / dt:.1f} imgs/s", file=sys.stderr)
    from profile_bert import print_table, summarize
    data = summarize(args.logdir)
    if data:
        print_table(data, args.top)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--depth", type=int, default=50,
                    choices=(18, 34, 50, 101, 152))
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--hw", type=int, default=64)
    ap.add_argument("--nclass", type=int, default=1000)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--xprof", action="store_true",
                    help="device-side capture via jax.profiler + "
                    "hlo_stats (TPU runs) instead of the host tracer")
    ap.add_argument("--logdir", default="/tmp/resnet_profile")
    args = ap.parse_args()
    if args.xprof:
        xprof_capture(args)
    else:
        op_profile(args)


if __name__ == "__main__":
    main()

"""Per-op device profile of the ResNet50 Model.train_batch step (the
bench.py resnet leg), via xprof hlo_stats — same harness as
tools/profile_bert.py."""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def capture(batch: int, steps: int, logdir: str):
    import time
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle

    paddle.seed(0)
    net = paddle.vision.models.resnet50(num_classes=1000)
    model = paddle.Model(net)
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss(), amp_configs="O2")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(batch, 3, 224, 224), jnp.float32)
    y = jnp.asarray(rng.randint(0, 1000, (batch, 1)), jnp.int32)
    model.train_batch([x], [y])
    p0 = next(iter(net.parameters()))
    jax.block_until_ready(p0._data)
    with jax.profiler.trace(logdir):
        t0 = time.perf_counter()
        for _ in range(steps):
            model.train_batch([x], [y])
        jax.block_until_ready(p0._data)
        dt = time.perf_counter() - t0
    print(f"[capture] {steps} steps in {dt:.3f}s -> "
          f"{batch * steps / dt:.1f} imgs/s", file=sys.stderr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--logdir", default="/tmp/resnet_profile")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--reuse", action="store_true")
    args = ap.parse_args()
    if not args.reuse:
        os.makedirs(args.logdir, exist_ok=True)
        capture(args.batch, args.steps, args.logdir)
    from profile_bert import summarize, print_table
    data = summarize(args.logdir)
    if data:
        print_table(data, args.top)


if __name__ == "__main__":
    main()

"""Bench the routed flash_attention (mode gate as shipped) vs XLA math
across T, using device-time-truthful big-loop timing: run N calls inside
one jit (lax.scan chaining) so per-dispatch tunnel overhead amortizes.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax


def chain_bench(f, args, iters=8):
    """loss-like scalar chained through iterations inside ONE jit."""
    def body(c, _):
        out = f(*[a + c.astype(a.dtype) for a in args])
        return jnp.sum(out.astype(jnp.float32)) * 1e-20, None

    @jax.jit
    def run(args):
        c, _ = lax.scan(body, jnp.zeros(()), None,
                        length=iters)
        return c

    r = run(args)
    float(r)
    t0 = time.perf_counter()
    r = run(args)
    float(r)
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--B", type=int, default=128)
    ap.add_argument("--H", type=int, default=12)
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--Ts", default="512,1024")
    ap.add_argument("--grad", action="store_true")
    args = ap.parse_args()
    import importlib
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    for T in [int(t) for t in args.Ts.split(",")]:
        B = args.B * 512 // T  # constant tokens
        rng = np.random.RandomState(0)
        shape = (B, T, args.H, args.d)
        q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
        flops = 2 * 2 * B * args.H * T * T * args.d

        def pall(q):
            return fa.flash_attention(q, q, q, causal=True)

        def xla(q):
            qf = jnp.swapaxes(q, 1, 2).reshape(B * args.H, T, args.d)
            o = fa._xla_attention(qf, qf, qf, 1.0 / np.sqrt(args.d), True)
            return jnp.swapaxes(o.reshape(B, args.H, T, args.d), 1, 2)

        for name, f in [("pallas", pall), ("xla", xla)]:
            if args.grad:
                g = lambda q, f=f: jax.grad(
                    lambda x: jnp.sum(f(x).astype(jnp.float32)))(q)
                t = chain_bench(g, (q,))
                eff = 3 * flops / t / 1e12
            else:
                t = chain_bench(f, (q,))
                eff = flops / t / 1e12
            print(f"T={T:5d} B={B:4d} {name:7s} "
                  f"{'fwd+bwd' if args.grad else 'fwd':7s} "
                  f"{t*1e3:8.2f} ms  ({eff:5.1f} T eff)")


if __name__ == "__main__":
    main()

"""Bench the routed flash_attention (mode gate as shipped) vs XLA math
across T.  Default timing is DEVICE SELF-TIME from an xprof capture of
the chained loop (contention-immune on the shared axon chip); pass
--wall for wall-clock.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from _device_bench import device_time, wall_time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--B", type=int, default=128)
    ap.add_argument("--H", type=int, default=12)
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--Ts", default="512,1024")
    ap.add_argument("--grad", action="store_true")
    ap.add_argument("--wall", action="store_true")
    args = ap.parse_args()
    use_wall = args.wall
    if not use_wall:
        try:
            # probe the exact dependency device_time uses, not just the
            # top-level package (version skew can lack the converter)
            from xprof.convert import raw_to_tool_data  # noqa: F401
        except ImportError:
            print("xprof converter not importable: falling back to "
                  "--wall timing (contention-sensitive on shared chips)",
                  file=sys.stderr)
            use_wall = True
    if use_wall:
        timer = wall_time
    else:
        def timer(f, a):
            # device_time returns None when the capture produced no
            # xplane, 0.0 when hlo_stats had no self-time rows, and can
            # raise on converter skew; fall back to wall per-row.
            try:
                t = device_time(f, a)
            except Exception as e:
                print(f"device capture failed ({e!r}): wall timing for "
                      "this row", file=sys.stderr)
                t = None
            if not t:
                t = wall_time(f, a)
            return t
    import importlib
    fa = importlib.import_module("paddle_tpu.ops.pallas.flash_attention")

    for T in [int(t) for t in args.Ts.split(",")]:
        B = args.B * 512 // T  # constant tokens
        rng = np.random.RandomState(0)
        shape = (B, T, args.H, args.d)
        q = jnp.asarray(rng.randn(*shape), jnp.bfloat16)
        flops = 2 * 2 * B * args.H * T * T * args.d

        def pall(q):
            return fa.flash_attention(q, q, q, causal=True)

        def xla(q):
            qf = jnp.swapaxes(q, 1, 2).reshape(B * args.H, T, args.d)
            o = fa._xla_attention(qf, qf, qf, 1.0 / np.sqrt(args.d), True)
            return jnp.swapaxes(o.reshape(B, args.H, T, args.d), 1, 2)

        for name, f in [("pallas", pall), ("xla", xla)]:
            if args.grad:
                g = lambda q, f=f: jax.grad(
                    lambda x: jnp.sum(f(x).astype(jnp.float32)))(q)
                t = timer(g, (q,))
                eff = 3 * flops / t / 1e12
            else:
                t = timer(f, (q,))
                eff = flops / t / 1e12
            print(f"T={T:5d} B={B:4d} {name:7s} "
                  f"{'fwd+bwd' if args.grad else 'fwd':7s} "
                  f"{t*1e3:8.2f} ms  ({eff:5.1f} T eff)")


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""AST lint over the paddle_tpu framework itself (CI gate).

The IR passes in ``paddle_tpu/static/passes`` sanitize user *programs*;
this tool sanitizes the *framework source* — the defect classes that
repeatedly cost debugging time on the TPU path:

- ``HS01 host-sync-in-impl``: a jit-traceable op impl (a function passed
  to ``core.dispatch.dispatch`` or decorated ``@register_kernel``) calls
  ``.item()``, ``float()/int()/bool()``, or ``np.asarray/np.array`` on a
  traced argument.  Under ``jax.jit``/static capture these either raise
  a ``TracerArrayConversionError`` at trace time or, worse, silently
  force a device->host sync per call in eager mode.
- ``MD01 mutable-default-arg``: ``def f(x=[])`` / ``{}`` / ``set()`` —
  shared across calls; a classic source of cross-test state bleed.
- ``VJ01 custom-vjp-without-defvjp``: a function is wrapped in
  ``jax.custom_vjp`` (decorator or ``functools.partial`` form) but the
  module never calls ``<name>.defvjp(...)`` — dispatching such an op
  raises ``CustomVJPException`` only when someone first differentiates
  it, typically deep inside a user's training loop.
- ``FL01 import-time-flag-read``: module-top-level ``get_flag(...)``
  freezes the flag's value at import, so ``set_flags`` after import is
  silently ignored for that code path.
- ``DT01 float64-promotion``: op impls (and, because pass rewrites run
  inside jit too, every function in ``static/passes``) referencing
  ``np.float64``/``np.double``, or calling a numpy array constructor
  on float literals without a ``dtype=`` — NumPy defaults to float64,
  which silently widens the op's output (or the whole fused region)
  off the TPU-native f32/bf16 path.

Usage::

    python tools/framework_lint.py [paths...] [--baseline FILE]
    python tools/framework_lint.py --write-baseline   # re-seed baseline

Exit status is nonzero iff a finding is NOT in the baseline file
(``tools/framework_lint_baseline.txt``) — pre-existing findings are
suppressed explicitly so only *new* violations fail CI.  Baseline keys
deliberately omit line numbers (``path|code|scope|detail``) so unrelated
edits don't invalidate them.
"""
from __future__ import annotations

import argparse
import ast
import os
import sys
from typing import Dict, List, Optional, Set

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "framework_lint_baseline.txt")

_HOST_SYNC_BUILTINS = {"float", "int", "bool"}
_NP_SYNC_FUNCS = {"asarray", "array"}
# numpy constructors that default to float64 when fed python floats
_NP_FLOAT_CTORS = {"array", "asarray", "full", "full_like", "ones",
                   "zeros", "arange", "linspace", "eye"}


class Finding:
    __slots__ = ("path", "line", "code", "scope", "detail", "message",
                 "occurrence")

    def __init__(self, path, line, code, scope, detail, message):
        self.path = path
        self.line = line
        self.code = code
        self.scope = scope
        self.detail = detail
        self.message = message
        self.occurrence = 0  # per-(path,code,scope,detail) index, set
        #                      by _assign_occurrences — keeps keys unique
        #                      so a baselined violation can't mask a NEW
        #                      identical one added to the same function

    def key(self) -> str:
        return (f"{self.path}|{self.code}|{self.scope}|{self.detail}"
                f"|{self.occurrence}")

    def __repr__(self):
        return (f"{self.path}:{self.line}: {self.code} [{self.scope}] "
                f"{self.message}")


def _call_name(func) -> str:
    """Dotted tail of a call target: Name 'f' -> 'f', Attribute a.b.f ->
    'a.b.f' (best effort)."""
    parts = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _names_in(node) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _is_custom_vjp_decorator(dec) -> bool:
    """@jax.custom_vjp or @functools.partial(jax.custom_vjp, ...)."""
    name = _call_name(dec.func) if isinstance(dec, ast.Call) else \
        _call_name(dec)
    if name.endswith("custom_vjp"):
        return True
    if isinstance(dec, ast.Call) and name.endswith("partial") and dec.args:
        return _call_name(dec.args[0]).endswith("custom_vjp")
    return False


def _mutable_default(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        return _call_name(node.func) in ("list", "dict", "set")
    return False


def _func_params(fn) -> Set[str]:
    a = fn.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _collect_impl_functions(tree) -> Dict[str, ast.AST]:
    """Functions that execute under jax tracing: dispatch targets
    (2nd positional arg of a ``dispatch(...)`` call, by name or inline
    lambda) and ``@register_kernel(...)``-decorated defs."""
    impl_names: Set[str] = set()
    inline: List[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _call_name(node.func).split(".")[-1] == "dispatch" and \
                len(node.args) >= 2:
            target = node.args[1]
            if isinstance(target, ast.Name):
                impl_names.add(target.id)
            elif isinstance(target, (ast.Lambda,)):
                inline.append(target)
    impls: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in impl_names:
                impls.setdefault(node.name, node)
            for dec in node.decorator_list:
                dname = _call_name(dec.func) if isinstance(dec, ast.Call) \
                    else _call_name(dec)
                if dname.split(".")[-1] == "register_kernel":
                    impls.setdefault(node.name, node)
    for i, lam in enumerate(inline):
        impls[f"<lambda#{i}>"] = lam
    return impls


def _walk_skipping_defs(root):
    """ast.walk that does NOT descend into nested function defs — their
    bodies have their own parameter scope (and their own HS01 run if
    they are dispatched themselves)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


def _lint_host_sync(path, scope, fn, out: List[Finding]):
    params = _func_params(fn)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # nested def at statement level: own scope
        for node in _walk_skipping_defs(stmt):
            if not isinstance(node, ast.Call):
                continue
            cname = _call_name(node.func)
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                out.append(Finding(
                    path, node.lineno, "HS01", scope, "item",
                    "`.item()` inside a jit-traceable op impl forces a "
                    "device->host sync / fails under tracing"))
                continue
            tail = cname.split(".")
            if len(tail) >= 2 and tail[-2] in ("np", "numpy") and \
                    tail[-1] in _NP_SYNC_FUNCS and node.args:
                touched = _names_in(node.args[0]) & params
                if touched:
                    out.append(Finding(
                        path, node.lineno, "HS01", scope,
                        f"np.{tail[-1]}:{sorted(touched)[0]}",
                        f"`np.{tail[-1]}` on traced arg "
                        f"'{sorted(touched)[0]}' materialises on host "
                        "(use jnp, or mark the op __shape_probed__)"))
            elif cname in _HOST_SYNC_BUILTINS and len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Name) and \
                    node.args[0].id in params:
                out.append(Finding(
                    path, node.lineno, "HS01", scope,
                    f"{cname}:{node.args[0].id}",
                    f"`{cname}()` on traced arg '{node.args[0].id}' "
                    "raises ConcretizationTypeError under jit"))


def _float_literal_in(node) -> bool:
    return any(isinstance(c, ast.Constant) and isinstance(c.value, float)
               for c in ast.walk(node))


def _lint_dtype_flow(path, scope, fn, out: List[Finding]):
    """DT01 over one function body (nested defs get their own run)."""
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in _walk_skipping_defs(stmt):
            if isinstance(node, ast.Attribute) and \
                    node.attr in ("float64", "double"):
                tail = _call_name(node).split(".")
                if len(tail) >= 2 and tail[-2] in ("np", "numpy"):
                    out.append(Finding(
                        path, node.lineno, "DT01", scope, node.attr,
                        f"np.{node.attr} widens to f64 off the "
                        "TPU-native f32/bf16 path — use an explicit "
                        "32-bit (or jnp) dtype"))
            elif isinstance(node, ast.Call):
                tail = _call_name(node.func).split(".")
                if len(tail) >= 2 and tail[-2] in ("np", "numpy") and \
                        tail[-1] in _NP_FLOAT_CTORS and \
                        not any(kw.arg == "dtype" for kw in node.keywords) \
                        and any(_float_literal_in(a) for a in node.args):
                    out.append(Finding(
                        path, node.lineno, "DT01", scope,
                        f"np.{tail[-1]}",
                        f"`np.{tail[-1]}` on a float literal without "
                        "dtype= produces float64 — pass dtype=np.float32 "
                        "(or use jnp, which stays weak-typed)"))


def _all_functions(tree) -> Dict[str, ast.AST]:
    """Every named function in the module (pass files: rewrite helpers
    and fused impls run under jit even though they are not dispatch
    targets)."""
    funcs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            funcs.setdefault(node.name, node)
    return funcs


def _module_level_nodes(tree):
    """Every AST node whose code runs at import time: module/class-body
    statements and their sub-expressions, plus function decorators and
    default-arg expressions — but never the inside of a function body."""
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # the def statement's decorators and defaults evaluate at
            # import; the body does not
            stack.extend(node.decorator_list)
            stack.extend(d for d in node.args.defaults)
            stack.extend(d for d in node.args.kw_defaults if d is not None)
            continue
        if isinstance(node, ast.Lambda):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def lint_source(src: str, path: str) -> List[Finding]:
    tree = ast.parse(src)
    findings: List[Finding] = []

    # HS01 — host syncs inside jit-traceable impls
    for name, fn in _collect_impl_functions(tree).items():
        _lint_host_sync(path, name, fn, findings)

    # DT01 — float64 promotion in impls; pass files are linted whole
    # (their rewrite helpers and fused composites execute under jit)
    dt_scopes = dict(_collect_impl_functions(tree))
    if "static/passes/" in path.replace(os.sep, "/"):
        for name, fn in _all_functions(tree).items():
            dt_scopes.setdefault(name, fn)
    for name, fn in dt_scopes.items():
        _lint_dtype_flow(path, name, fn, findings)

    # MD01 — mutable default args (whole file)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for default in list(node.args.defaults) + \
                [d for d in node.args.kw_defaults if d is not None]:
            if _mutable_default(default):
                kind = type(default).__name__.lower() \
                    if not isinstance(default, ast.Call) \
                    else _call_name(default.func)
                findings.append(Finding(
                    path, default.lineno, "MD01", node.name, kind,
                    f"mutable default argument ({kind}) is shared "
                    "across calls — default to None and construct "
                    "inside"))

    # VJ01 — custom_vjp without defvjp
    vjp_defs: Dict[str, int] = {}
    defvjp_called: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_custom_vjp_decorator(d)
                   for d in node.decorator_list):
                vjp_defs[node.name] = node.lineno
        elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call) and _call_name(
                node.value.func).endswith("custom_vjp"):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    vjp_defs[tgt.id] = node.lineno
        elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr == "defvjp":
            base = node.func.value
            if isinstance(base, ast.Name):
                defvjp_called.add(base.id)
            elif isinstance(base, ast.Attribute):
                defvjp_called.add(base.attr)
    for name, line in sorted(vjp_defs.items()):
        if name not in defvjp_called:
            findings.append(Finding(
                path, line, "VJ01", name, "defvjp",
                f"'{name}' is wrapped in jax.custom_vjp but this module "
                "never calls its .defvjp(fwd, bwd) — differentiating "
                "the op will raise at first use"))

    # FL01 — import-time flag reads
    for node in _module_level_nodes(tree):
        if isinstance(node, ast.Call) and \
                _call_name(node.func).split(".")[-1] == "get_flag":
            arg = node.args[0].value if node.args and isinstance(
                node.args[0], ast.Constant) else "?"
            findings.append(Finding(
                path, node.lineno, "FL01", "<module>", str(arg),
                f"get_flag({arg!r}) at import time freezes the "
                "value — read it inside the function that needs it"))
    return _assign_occurrences(findings)


def _assign_occurrences(findings: List[Finding]) -> List[Finding]:
    counts: Dict[str, int] = {}
    for f in sorted(findings, key=lambda f: f.line):
        base = f"{f.path}|{f.code}|{f.scope}|{f.detail}"
        f.occurrence = counts.get(base, 0)
        counts[base] = f.occurrence + 1
    return findings


def lint_paths(paths: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    for root in paths:
        if os.path.isfile(root):
            files = [root]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        for f in sorted(files):
            rel = os.path.relpath(f, REPO)
            try:
                with open(f, encoding="utf-8") as fh:
                    src = fh.read()
            except OSError as e:
                print(f"framework_lint: cannot read {rel}: {e}",
                      file=sys.stderr)
                continue
            try:
                findings.extend(lint_source(src, rel))
            except SyntaxError as e:
                findings.append(Finding(rel, e.lineno or 0, "SYN", "?",
                                        "syntax", f"syntax error: {e}"))
    return findings


def load_baseline(path: str) -> Set[str]:
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        return {line.strip() for line in f
                if line.strip() and not line.startswith("#")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "paddle_tpu")])
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-seed the suppression list from the current "
                         "findings (reviewed, never automatic in CI)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding (exit 1 if any)")
    args = ap.parse_args(argv)

    findings = lint_paths(args.paths)
    if args.write_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write("# framework_lint baseline — pre-existing findings "
                    "suppressed in CI.\n# Regenerate (after review!) "
                    "with: python tools/framework_lint.py "
                    "--write-baseline\n")
            for k in sorted({fi.key() for fi in findings}):
                f.write(k + "\n")
        print(f"framework_lint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(args.baseline, REPO)}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(args.baseline)
    new = [f for f in findings if f.key() not in baseline]
    for f in findings:
        tag = "" if f.key() in baseline else "  <-- NEW"
        print(f"{f!r}{tag}")
    print(f"framework_lint: {len(findings)} finding(s), "
          f"{len(findings) - len(new)} baseline-suppressed, "
          f"{len(new)} new")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Config-driven op micro-benchmark harness.

Reference parity: ``paddle/fluid/operators/benchmark/op_tester.cc`` (+
``op_tester_config``) — per-op timing driven by small config entries —
and the CI gates ``tools/test_ci_op_benchmark.sh`` /
``tools/check_op_benchmark_result.py``.

Usage:
    python tools/op_bench.py                      # built-in suite
    python tools/op_bench.py --config my.json     # custom entries
    python tools/op_bench.py --baseline old.json  # regression compare

Config entry: {"op": "matmul", "shapes": [[1024,1024],[1024,1024]],
"dtype": "float32", "kwargs": {}, "repeat": 30}
Emits one JSON line per op: {"op", "eager_us", "jit_us", "shapes"}.
A baseline compare fails (exit 1) when jit time regresses >20%
(check_op_benchmark_result.py's relative gate).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_SUITE = [
    {"op": "matmul", "shapes": [[512, 512], [512, 512]]},
    {"op": "add", "shapes": [[1024, 1024], [1024, 1024]]},
    {"op": "multiply", "shapes": [[1024, 1024], [1024, 1024]]},
    {"op": "sum", "shapes": [[2048, 512]]},
    {"op": "softmax", "shapes": [[256, 1024]], "module": "nn.functional"},
    {"op": "relu", "shapes": [[2048, 512]], "module": "nn.functional"},
    {"op": "exp", "shapes": [[1024, 1024]]},
    {"op": "transpose", "shapes": [[1024, 1024]],
     "kwargs": {"perm": [1, 0]}},
    {"op": "concat", "shapes": [[512, 512], [512, 512]], "is_list": True},
    {"op": "layer_norm", "shapes": [[256, 1024]],
     "module": "nn.functional", "kwargs": {"normalized_shape": [1024]}},
]


def _resolve(paddle, entry):
    mod = paddle
    for part in entry.get("module", "").split("."):
        if part:
            mod = getattr(mod, part)
    return getattr(mod, entry["op"])


def bench_entry(paddle, jax, np, entry):
    import jax.numpy as jnp
    fn = _resolve(paddle, entry)
    rs = np.random.RandomState(0)
    dtype = entry.get("dtype", "float32")
    args = [paddle.to_tensor(rs.rand(*s).astype(dtype))
            for s in entry["shapes"]]
    kwargs = entry.get("kwargs", {})
    call = (lambda: fn(args, **kwargs)) if entry.get("is_list") \
        else (lambda: fn(*args, **kwargs))
    repeat = entry.get("repeat", 30)

    out = call()                      # warm the eager path
    jax.block_until_ready(out._data if hasattr(out, "_data") else out)
    t0 = time.perf_counter()
    for _ in range(repeat):
        out = call()
    jax.block_until_ready(out._data if hasattr(out, "_data") else out)
    eager_us = (time.perf_counter() - t0) / repeat * 1e6

    raw = [a._data for a in args]

    def jfn(*arrs):
        ts = [paddle.Tensor(a) for a in arrs]
        with paddle.no_grad():
            o = fn(ts, **kwargs) if entry.get("is_list") \
                else fn(*ts, **kwargs)
        return o._data if hasattr(o, "_data") else o

    jitted = jax.jit(jfn)
    jax.block_until_ready(jitted(*raw))
    t0 = time.perf_counter()
    for _ in range(repeat):
        r = jitted(*raw)
    jax.block_until_ready(r)
    jit_us = (time.perf_counter() - t0) / repeat * 1e6
    return {"op": entry["op"], "shapes": entry["shapes"],
            "eager_us": round(eager_us, 1), "jit_us": round(jit_us, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", help="json list of entries")
    ap.add_argument("--baseline", help="previous output for regression "
                    "compare (>20%% jit regression fails)")
    ap.add_argument("--out", help="write results json here")
    args = ap.parse_args()

    import jax
    env = os.environ.get("JAX_PLATFORMS")
    if env and jax.config.jax_platforms != env:
        jax.config.update("jax_platforms", env)
    import numpy as np
    import paddle_tpu as paddle

    suite = DEFAULT_SUITE
    if args.config:
        suite = json.load(open(args.config))
    results = []
    for entry in suite:
        r = bench_entry(paddle, jax, np, entry)
        print(json.dumps(r))
        results.append(r)
    if args.out:
        json.dump(results, open(args.out, "w"), indent=1)
    if args.baseline:
        base = {b["op"]: b for b in json.load(open(args.baseline))}
        bad = [r for r in results
               if r["op"] in base
               and r["jit_us"] > 1.2 * base[r["op"]]["jit_us"]]
        if bad:
            for r in bad:
                print(f"REGRESSION {r['op']}: {r['jit_us']}us vs "
                      f"{base[r['op']]['jit_us']}us", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

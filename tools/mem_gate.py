#!/usr/bin/env python
"""CI memory-accounting gate: goodput decomposition, checkpoint-badput
attribution, and OOM forensics — the ISSUE-16 memscope layer, end to
end in fresh subprocesses.

Phase 1 (goodput): a supervised-style ``Model.fit`` with an
AsyncCheckpointer under a fixed ``ckpt.write:delay`` chaos spec.  The
delayed background writes must surface as checkpoint-bucket badput
(the fit-end drain), the chaos injections must land at EXACT flight
counts, the goodput fractions must sum to 1 +- 0.01, and the
``goodput.r0.g0.json`` doc must land in PADDLE_FLIGHT_DIR.  The same
subprocess first pins the zero-cost contract: with
``FLAGS_mem_accounting=0`` a full fit leaves no ``mem.*`` /
``*.goodput.*`` gauges and no compile-ledger entries — the hooks are
one module-predicate read.

Phase 2 (OOM forensics): a PagedGenerationEngine built on a
deliberately tiny block pool is asked for a prompt that cannot fit.
The typed ``BlockPoolExhausted`` shed must write the forensics
artifact ``oom.r0.g0.json`` — census (what was resident, by tag),
block-pool occupancy, prefix-cache occupancy, and the flight-ring
tail — record a ``mem.oom`` flight event, and still answer the client
with the typed ``RequestRejected(reason="kv_blocks")``.

Wired into tools/run_all_tests.sh.
"""
import json
import os
import subprocess
import sys
import tempfile
import textwrap

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

GOODPUT = """
import json, os, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.profiler import flight, memscope, metrics

out_path, ckpt_dir = sys.argv[1], sys.argv[2]
STEPS = 6


class Ds(paddle.io.Dataset):
    def __init__(self):
        r = np.random.RandomState(0)
        self.x = r.rand(STEPS * 4, 8).astype("float32")
        self.y = r.rand(STEPS * 4, 2).astype("float32")

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


def build():
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(8, 16),
                               paddle.nn.ReLU(),
                               paddle.nn.Linear(16, 2))
    m = paddle.Model(net)
    m.prepare(paddle.optimizer.Adam(1e-2,
                                    parameters=net.parameters()),
              paddle.nn.MSELoss())
    return m


# -- zero-cost pin: accounting off => zero memscope artifacts ---------
assert not memscope.active, "FLAGS_mem_accounting should default off"
memscope.reset()
build().fit(Ds(), batch_size=4, epochs=1, verbose=0, shuffle=False)
leftovers = [n for n in metrics.snapshot()
             if n.startswith("mem.") or ".goodput." in n]
assert leftovers == [], f"memscope gauges with accounting off: {leftovers}"
assert memscope.compile_count() == 0, \\
    "compile ledger recorded entries with accounting off"

# -- armed fit under delayed checkpoint writes ------------------------
paddle.set_flags({"FLAGS_mem_accounting": 1,
                  "FLAGS_flight_recorder": 1,
                  "FLAGS_chaos_spec": "ckpt.write:delay=0.25@1-2"})
flight.clear()
m = build()
ck = ckpt.AsyncCheckpointer(ckpt_dir, save_interval_steps=1)
m.fit(Ds(), batch_size=4, epochs=1, verbose=0, shuffle=False,
      checkpointer=ck)
ck.close()
doc = m._last_goodput
assert doc is not None, "fit with accounting on left no goodput doc"

fr = doc["fractions"]
total = sum(fr.values())
assert abs(total - 1.0) <= 0.01, f"fractions sum {total} != 1"
assert doc["buckets_s"]["checkpoint"] >= 0.25, \\
    f"delayed ckpt writes not charged to the checkpoint bucket: {doc}"
assert fr["productive"] > 0, f"no productive time recorded: {doc}"
assert doc["compiles"] >= 1, "first-step jit compile missing from ledger"

counts = flight.counts()
assert counts.get("chaos.ckpt.write") == 2, \\
    f"expected exactly 2 ckpt.write injections, got {counts}"
assert counts.get("mem.compile", 0) >= 1, \\
    f"compile-ledger flight event missing: {counts}"

gp_path = os.path.join(os.environ["PADDLE_FLIGHT_DIR"],
                       "goodput.r0.g0.json")
assert os.path.exists(gp_path), f"goodput doc not written to {gp_path}"
with open(gp_path) as f:
    exported = json.load(f)
assert exported["fractions"] == doc["fractions"], \\
    "exported goodput doc disagrees with the in-process one"
g = metrics.get("train.goodput.productive")
assert g is not None and g.value == fr["productive"], \\
    "train.goodput.productive gauge missing or stale"

ledger = memscope.compile_entries()
assert any(e["site"] == "hapi.train_step" and e["cause"] == "new-site"
           for e in ledger), f"train-step compile not in ledger: {ledger}"

with open(out_path, "w") as f:
    json.dump({"fractions": fr, "counts": counts}, f)
print("goodput leg ok:", {k: round(v, 4) for k, v in fr.items()})
"""

OOM = """
import json, os, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import serving
from paddle_tpu.models import GPT, GPTConfig
from paddle_tpu.profiler import flight, memscope

paddle.seed(0)
paddle.set_flags({"FLAGS_mem_accounting": 1,
                  "FLAGS_flight_recorder": 1})
flight.clear()

net = GPT(GPTConfig(vocab_size=97, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=128, ffn_mult=2))
# a pool of 2 blocks (32 tokens) serving a 40-token prompt: the
# admission-time allocation MUST exhaust -> typed shed + forensics
engine = serving.PagedGenerationEngine(
    net, serving.GenerationEngineConfig(
        max_slots=2, max_length=128, max_new_tokens=4, block_size=16,
        num_blocks=2, prefix_cache_blocks=2, name="memgate"))
try:
    prompt = np.arange(1, 41, dtype=np.int32) % 90 + 1
    try:
        engine.generate(prompt, timeout=300)
        raise AssertionError("40-token prompt fit in a 2-block pool?")
    except serving.RequestRejected as e:
        assert e.reason == "kv_blocks", \\
            f"expected the typed kv_blocks shed, got {e.reason!r}"
finally:
    engine.close()

counts = flight.counts()
assert counts.get("mem.oom", 0) >= 1, \\
    f"shed left no mem.oom flight event: {counts}"

path = os.path.join(os.environ["PADDLE_FLIGHT_DIR"], "oom.r0.g0.json")
assert os.path.exists(path), f"forensics dump not written to {path}"
with open(path) as f:
    doc = json.load(f)
assert doc["context"] == "kv_shed:memgate", doc["context"]
census = doc["census"]
assert census["live_bytes_total"] > 0, "census empty in forensics dump"
assert "params" in census["tags"] and census["tags"]["params"] > 0, \\
    f"params tag missing from the dump census: {census['tags']}"
pool = doc["pool"]
assert pool["num_blocks"] == 2, pool
assert pool["used"] == 0, f"shed leaked block refs: {pool}"
assert "prefix_cache" in doc, "prefix-cache occupancy missing"
evs = doc["flight"]["events"]
assert any(e["cat"] == "mem" and e["event"] == "oom" for e in evs), \\
    "flight tail in the dump lacks the mem.oom event itself"
print("oom leg ok:", {"tags": census["tags"], "pool": pool})
"""


def run_leg(name, code, *argv):
    with tempfile.TemporaryDirectory(prefix=f"memgate_{name}_") as d:
        env = dict(os.environ)
        env["PADDLE_FLIGHT_DIR"] = os.path.join(d, "flight")
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        args = [a.replace("@TMP@", d) for a in argv]
        p = subprocess.run(
            [sys.executable, "-c", textwrap.dedent(code), *args],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=600)
        sys.stdout.write(p.stdout)
        if p.returncode != 0:
            sys.stderr.write(p.stderr)
            print(f"mem_gate: {name} leg FAILED", file=sys.stderr)
            return False
        return True


def main():
    ok = run_leg("goodput", GOODPUT, "@TMP@/out.json", "@TMP@/ckpt")
    ok = run_leg("oom", OOM) and ok
    if not ok:
        return 1
    print("mem_gate: all legs passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Isolate the flagship FFN matmul shapes on device to pin the XLA
emitter behavior the round-4 profile flagged (down-projection chain at
~half the up-projection's TFLOP/s).

Chained big-loop timing (lax.scan inside one jit) so the axon tunnel's
per-dispatch latency amortizes; each variant prints achieved TFLOP/s.
"""
import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax


def chain_bench(f, args, weights, iters=8):
    """f(*args, *weights); args get the carry perturbation (data
    dependence chains the iterations), weights pass through untouched.
    Everything is an explicit jit argument — closure constants embed as
    HLO literals, which the axon tunnel re-ships every call."""
    def body(c, _):
        out = f(*[a + c.astype(a.dtype) for a in args], *weights)
        return jnp.sum(out.astype(jnp.float32)) * 1e-20, None

    @jax.jit
    def run(args, weights):
        c, _ = lax.scan(body, jnp.zeros(()), None, length=iters)
        return c

    float(run(args, weights))
    t0 = time.perf_counter()
    float(run(args, weights))
    return (time.perf_counter() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--B", type=int, default=128)
    ap.add_argument("--T", type=int, default=512)
    ap.add_argument("--D", type=int, default=768)
    ap.add_argument("--F", type=int, default=3072)
    args = ap.parse_args()
    B, T, D, F = args.B, args.T, args.D, args.F
    # generate on-device: big host->device literals overflow the axon
    # tunnel's request-size limit (HTTP 413)
    ks = jax.random.split(jax.random.PRNGKey(0), 6)
    dev = jax.jit(lambda: (
        jax.random.normal(ks[0], (B, T, D), jnp.bfloat16),
        jax.random.normal(ks[1], (B * T, D), jnp.bfloat16),
        jax.random.normal(ks[2], (B, T, F), jnp.bfloat16),
        jax.random.normal(ks[3], (B * T, F), jnp.bfloat16),
        jax.random.normal(ks[4], (D, F), jnp.bfloat16) * 0.02,
        jax.random.normal(ks[5], (F, D), jnp.bfloat16) * 0.02,
    ))
    x, x2, up, up2, w_up, w_dn = jax.block_until_ready(dev())
    w_dnT = jax.block_until_ready(jax.jit(jnp.transpose)(w_dn))
    g = jnp.ones((D,), jnp.bfloat16)
    b = jnp.zeros((D,), jnp.bfloat16)
    mm = 2 * B * T * D * F  # flops of one up- or down-projection

    def ln(x, g, b, eps=1e-5):
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mu) * lax.rsqrt(var + eps) * g + b

    cases = [
        ("up 3d: (B,T,D)@(D,F)", lambda x, w: x @ w, (x,), (w_up,), mm),
        ("up 2d: (BT,D)@(D,F)", lambda x, w: x @ w, (x2,), (w_up,), mm),
        ("dn 3d: (B,T,F)@(F,D)", lambda u, w: u @ w, (up,), (w_dn,), mm),
        ("dn 2d: (BT,F)@(F,D)", lambda u, w: u @ w, (up2,), (w_dn,), mm),
        ("dn 3d via wT dot_general", lambda u, w: lax.dot_general(
            u, w, (((2,), (1,)), ((), ()))), (up,), (w_dnT,), mm),
        ("dn 3d +residual", lambda u, x, w: x + u @ w, (up, x),
         (w_dn,), mm),
        ("gelu+dn 3d", lambda u, x, w: x + jax.nn.gelu(u) @ w,
         (up, x), (w_dn,), mm),
        ("full ffn chain (ln,up,gelu,dn,res)",
         lambda x, wu, wd: x + jax.nn.gelu(ln(x, g, b) @ wu) @ wd, (x,),
         (w_up, w_dn), 2 * mm),
        ("full ffn f32-accum dn",
         lambda x, wu, wd: x + lax.dot_general(
             jax.nn.gelu(ln(x, g, b) @ wu), wd,
             (((2,), (0,)), ((), ())),
             preferred_element_type=jnp.float32).astype(x.dtype), (x,),
         (w_up, w_dn), 2 * mm),
    ]
    prof = os.environ.get("FFN_BENCH_PROFILE", "1") == "1"
    for name, f, a, w, flops in cases:
        if prof:
            t = profile_bench(name, f, a, w)
            if t is None:
                continue
        else:
            t = chain_bench(f, a, w)
        print(f"{name:42s} {t*1e3:8.3f} ms  {flops/t/1e12:6.1f} TF/s")


def profile_bench(name, f, args, weights, iters=8):
    """Device-truthful timing: capture an xprof trace of the chained
    loop and sum per-op *device self time* — wall clock through the
    shared axon tunnel swings 2-5x with other tenants' load, device op
    durations don't (how the r4 per-op tables were measured)."""
    import glob
    import json
    import shutil
    import tempfile
    from xprof.convert import raw_to_tool_data as rtd

    def body(c, _):
        out = f(*[a + c.astype(a.dtype) for a in args], *weights)
        return jnp.sum(out.astype(jnp.float32)) * 1e-20, None

    @jax.jit
    def run(args, weights):
        c, _ = lax.scan(body, jnp.zeros(()), None, length=iters)
        return c

    float(run(args, weights))  # compile outside the capture
    logdir = tempfile.mkdtemp(prefix="ffnprof_")
    try:
        with jax.profiler.trace(logdir):
            float(run(args, weights))
        paths = sorted(glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                                 recursive=True))
        if not paths:
            return None
        data, _ = rtd.xspace_to_tool_data([paths[-1]], "hlo_stats", {})
        if isinstance(data, bytes):
            data = data.decode()
        tbl = json.loads(data)
        ids = [c["id"] for c in tbl["cols"]]
        total = 0.0
        for row in tbl["rows"]:
            r = {i: (c or {}).get("v") for i, c in zip(ids, row["c"])}
            total += float(r.get("total_self_time") or 0.0)
        return total / 1e6 / iters  # us -> s, per iteration
    finally:
        shutil.rmtree(logdir, ignore_errors=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""conc-lint: static lock-order & blocking-under-lock analysis (CI gate).

``tools/framework_lint.py`` sanitizes the framework source for TPU
dispatch defects (HS01/MD01/VJ01/FL01); this module does the same for
the defect class that actually dominated PR 4-7 review: concurrency.
It resolves ``self._lock``-style lock attributes per class (and
module-global locks), walks every function with a held-lock scope
stack, follows method calls within a class (and module-level calls
within a module), and reports:

- ``LK01 lock-order-cycle``: the global lock acquisition-order graph
  (built from nested ``with``/``acquire`` scopes, including through
  intra-class method calls) contains a cycle — two threads
  interleaving those paths can deadlock.  Also covers the degenerate
  self-cycle: a non-reentrant ``Lock`` re-acquired on a path that
  already holds it.
- ``LK02 blocking-under-lock``: a call that can block indefinitely
  executes while a lock is held — ``queue.get/put`` and
  ``Future.result``/``.join()``/``.wait()`` without a timeout,
  ``subprocess``/socket ops, and XLA dispatch (``jit`` /
  ``lower(...)`` / ``.compile()`` / ``device_put``) — the exact shape
  of the PR 4/6/7 review findings (batcher wedged, duplicate cold
  compiles, trace-window corruption, close() hangs).
- ``LK03 unguarded-guarded-attr``: an attribute written under a lock
  somewhere in its class is written bare (no lock held) in another
  method — either the lock is pointless or the bare write races.
- ``TH01 unjoined-non-daemon-thread``: ``threading.Thread`` created
  with ``daemon`` unset/False and no reachable ``join()`` — leaks a
  thread that can wedge interpreter shutdown.

Scope contract (what the static side does NOT see): calls across
module boundaries and through function-valued arguments are not
followed — the runtime sanitizer (``paddle_tpu/utils/concurrency.py``,
``FLAGS_lock_san``) owns those orderings.  Together they bracket the
bug class from both sides.

Usage::

    python tools/conc_lint.py [paths...] [--baseline FILE]
    python tools/conc_lint.py --write-baseline   # re-seed (review!)

Exit status is nonzero iff a finding is NOT in the baseline
(``tools/conc_lint_baseline.txt``).  Baseline keys are line-stable
(``path|code|scope|detail|occurrence``) so unrelated edits don't
invalidate them, and entries may carry a trailing ``# justification``
comment — CI policy requires one per baselined finding.
"""
from __future__ import annotations

import argparse
import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from framework_lint import (Finding, _assign_occurrences,  # noqa: E402
                            _call_name)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "conc_lint_baseline.txt")

_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}
_REENTRANT_KINDS = {"RLock", "Condition"}  # threading.Condition defaults
#                                            to an RLock internally

# receivers whose .wait() is lock-coupled: waiting on the held lock's
# own condition RELEASES it (not a blocking hold)
_SUBPROCESS_TAILS = {"run", "check_call", "check_output", "call",
                     "communicate"}
_SOCKET_TAILS = {"recv", "recv_into", "accept", "connect", "sendall",
                 "makefile"}
_DISPATCH_TAILS = {"device_put", "block_until_ready", "jit"}


class _LockDef:
    __slots__ = ("node", "kind", "line")

    def __init__(self, node: str, kind: str, line: int):
        self.node = node      # graph node id, e.g. "engine.InferenceEngine._mlock"
        self.kind = kind      # Lock | RLock | Condition
        self.line = line

    @property
    def reentrant(self) -> bool:
        return self.kind in _REENTRANT_KINDS


class _Edge:
    __slots__ = ("src", "dst", "path", "line", "via")

    def __init__(self, src, dst, path, line, via):
        self.src = src
        self.dst = dst
        self.path = path
        self.line = line
        self.via = via


def _lock_ctor_kind(call: ast.AST) -> Optional[str]:
    """'Lock' | 'RLock' | 'Condition' when ``call`` constructs one
    (``threading.X()``, ``concurrency.X(...)``, bare ``X()`` from a
    ``from threading import Lock`` style import), else None."""
    if not isinstance(call, ast.Call):
        return None
    tail = _call_name(call.func).split(".")[-1]
    return _LOCK_CTORS.get(tail)


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'self._lock' / 'NAME' / 'a.b.c' as a dotted string, or None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class _FileAnalysis:
    """One source file's lock definitions, ordering edges, per-function
    summaries, and directly-emitted findings."""

    def __init__(self, path: str, modbase: str):
        self.path = path
        self.modbase = modbase
        # "self._x" (per class) / global name -> _LockDef
        self.class_locks: Dict[str, Dict[str, _LockDef]] = {}
        self.global_locks: Dict[str, _LockDef] = {}
        self.edges: List[_Edge] = []
        self.findings: List[Finding] = []
        # propagation tables: qualname -> direct lock nodes / callees
        self.fn_acquires: Dict[str, Set[str]] = {}
        self.fn_calls: Dict[str, Set[str]] = {}
        # deferred call-site edges: (held nodes, callee qual, line, scope)
        self.call_sites: List[Tuple[List[str], str, int, str]] = []
        self.lock_kinds: Dict[str, str] = {}

    # -- lock resolution ----------------------------------------------
    def resolve(self, expr: ast.AST, cls: Optional[str]
                ) -> Optional[_LockDef]:
        chain = _attr_chain(expr)
        if chain is None:
            return None
        if chain.startswith("self.") and cls is not None:
            return self.class_locks.get(cls, {}).get(chain[5:])
        if "." not in chain:
            return self.global_locks.get(chain)
        return None


# ---------------------------------------------------------------------------
# pass 1: collect lock definitions
# ---------------------------------------------------------------------------
def _collect_locks(tree: ast.Module, fa: _FileAnalysis):
    def node_id(cls: Optional[str], attr: str) -> str:
        base = f"{fa.modbase}.{cls}.{attr}" if cls else \
            f"{fa.modbase}.{attr}"
        return base

    def scan_assign(stmt, cls: Optional[str]):
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        else:
            return
        kind = _lock_ctor_kind(value)
        if kind is None:
            return
        for tgt in targets:
            chain = _attr_chain(tgt)
            if chain is None:
                continue
            if chain.startswith("self.") and cls is not None:
                attr = chain[5:]
                d = _LockDef(node_id(cls, attr), kind, stmt.lineno)
                fa.class_locks.setdefault(cls, {})[attr] = d
                fa.lock_kinds[d.node] = kind
            elif "." not in chain and cls is None:
                d = _LockDef(node_id(None, chain), kind, stmt.lineno)
                fa.global_locks[chain] = d
                fa.lock_kinds[d.node] = kind

    # module-level walk that skips def/class bodies: a global lock
    # assigned inside a top-level try/except or platform `if` is still
    # a module global and must resolve (or every rule goes blind on it)
    stack = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.ClassDef):
            # every `self.X = Lock()` anywhere in the class (methods,
            # nested closures) defines a class lock attribute
            for sub in ast.walk(node):
                scan_assign(sub, node.name)
            continue
        scan_assign(node, None)
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# pass 2: per-function scope walk
# ---------------------------------------------------------------------------
class _FnWalker:
    """Walk one function body with a held-lock stack, emitting
    acquisitions, call sites, blocking calls, and attribute writes."""

    def __init__(self, fa: _FileAnalysis, qual: str, cls: Optional[str],
                 fn: ast.AST, writes_out: Dict[str, List[Tuple]]):
        self.fa = fa
        self.qual = qual
        self.cls = cls
        self.fn = fn
        self.writes = writes_out   # attr -> [(guarded, qual, line)]
        fa.fn_acquires.setdefault(qual, set())
        fa.fn_calls.setdefault(qual, set())

    # -- emission ------------------------------------------------------
    def _acquired(self, held: List[_LockDef], lock: _LockDef, line: int):
        fa = self.fa
        fa.fn_acquires[self.qual].add(lock.node)
        for h in held:
            fa.edges.append(_Edge(h.node, lock.node, fa.path, line,
                                  f"in {self.qual}"))

    def _blocking(self, held: List[_LockDef], kind: str, line: int):
        lock = held[-1]
        self.fa.findings.append(Finding(
            self.fa.path, line, "LK02", self.qual,
            f"{lock.node}:{kind}",
            f"blocking call ({kind}) while holding '{lock.node}' — an "
            "unbounded wait under a lock turns one slow/wedged peer "
            "into a pile-up of every thread that needs the lock "
            "(add a timeout, or move the call outside the critical "
            "section)"))

    # -- helpers -------------------------------------------------------
    def _check_call(self, call: ast.Call, held: List[_LockDef]):
        """LK02 candidates + call-site recording, for one Call node."""
        fa = self.fa
        name = _call_name(call.func)
        tail = name.split(".")[-1]
        line = call.lineno
        has_timeout = any(kw.arg == "timeout" for kw in call.keywords)
        nonblocking = any(
            kw.arg in ("block", "blocking") and
            isinstance(kw.value, ast.Constant) and kw.value.value is False
            for kw in call.keywords)
        npos = len(call.args)

        # record intra-class / intra-module call sites for propagation
        # (held or not: LK03's lock-context pass needs the bare ones)
        if isinstance(call.func, ast.Attribute) and \
                isinstance(call.func.value, ast.Name) and \
                call.func.value.id == "self" and self.cls is not None:
            callee = f"{self.cls}.{call.func.attr}"
            fa.fn_calls[self.qual].add(callee)
            fa.call_sites.append(
                ([h.node for h in held], callee, line, self.qual))
        elif isinstance(call.func, ast.Name):
            callee = call.func.id
            fa.fn_calls[self.qual].add(callee)
            fa.call_sites.append(
                ([h.node for h in held], callee, line, self.qual))

        if not held:
            return
        recv = call.func.value if isinstance(call.func, ast.Attribute) \
            else None
        if tail in ("wait", "wait_for") and recv is not None:
            # positional timeouts count, but a literal None does not:
            # wait(t) / wait_for(pred, t) are bounded, wait(None) /
            # wait_for(pred, None) are unbounded
            def _timed_pos(idx):
                return npos > idx and not (
                    isinstance(call.args[idx], ast.Constant) and
                    call.args[idx].value is None)
            timed = has_timeout or \
                (tail == "wait" and _timed_pos(0)) or \
                (tail == "wait_for" and _timed_pos(1))
            if not timed:
                # cond.wait() releases ONLY the cond's own lock; every
                # OTHER held lock stays held for the whole park.
                # Exempt the wait only for the receiver's own lock.
                d = self.fa.resolve(recv, self.cls)
                others = held if d is None else \
                    [h for h in held if h.node != d.node]
                if others:
                    self._blocking(others, "wait", line)
            return
        if tail == "get" and recv is not None and npos == 0 and \
                not has_timeout and not nonblocking:
            self._blocking(held, "queue.get", line)
        elif tail == "put" and recv is not None and npos == 1 and \
                not has_timeout and not nonblocking:
            self._blocking(held, "queue.put", line)
        elif tail == "result" and recv is not None and npos == 0 and \
                not has_timeout:
            self._blocking(held, "Future.result", line)
        elif tail == "join" and recv is not None and npos == 0 and \
                not has_timeout:
            self._blocking(held, "join", line)
        elif tail in _SUBPROCESS_TAILS and not has_timeout and (
                "subprocess" in name or tail == "communicate"):
            self._blocking(held, f"subprocess.{tail}", line)
        elif tail in _SOCKET_TAILS and recv is not None and \
                not has_timeout and tail not in ("connect",):
            self._blocking(held, f"socket.{tail}", line)
        elif tail == "connect" and recv is not None and npos == 1 and \
                not has_timeout:
            self._blocking(held, "socket.connect", line)
        elif tail in _DISPATCH_TAILS:
            self._blocking(held, f"dispatch.{tail}", line)
        elif tail == "compile" and recv is not None and npos == 0 and \
                not call.keywords:
            self._blocking(held, "dispatch.compile", line)
        elif tail == "lower" and recv is not None and \
                (npos > 0 or call.keywords):
            self._blocking(held, "dispatch.lower", line)

    def _scan_expr(self, node: ast.AST, held: List[_LockDef]):
        """Scan an expression tree (no statements inside) for calls and
        attribute writes."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_call(sub, held)

    def _note_writes(self, stmt: ast.stmt, held: List[_LockDef]):
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AugAssign):
            targets = [stmt.target]
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]   # bare `self.x: T` declares, not writes
        for tgt in targets:
            for t in ast.walk(tgt):
                chain = _attr_chain(t) if isinstance(t, ast.Attribute) \
                    else None
                if chain and chain.startswith("self.") and \
                        "." not in chain[5:]:
                    attr = chain[5:]
                    if self.cls is not None and attr not in \
                            self.fa.class_locks.get(self.cls, {}):
                        self.writes.setdefault(attr, []).append(
                            (bool(held), self.qual, stmt.lineno))

    # -- statement walk ------------------------------------------------
    def walk(self):
        self._walk_body(self.fn.body, [])

    def _walk_body(self, body: List[ast.stmt], held: List[_LockDef]):
        # .acquire()/.release() pairs extend the held set for the REST
        # of this body (a conservative but simple model of manual
        # acquire; `with` is the structured path below)
        local_held = list(held)
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue   # separate analysis unit
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                entered = list(local_held)
                for item in stmt.items:
                    self._scan_expr(item.context_expr, entered)
                    lock = self.fa.resolve(item.context_expr, self.cls)
                    if lock is not None:
                        self._acquired(entered, lock, stmt.lineno)
                        entered = entered + [lock]
                self._walk_body(stmt.body, entered)
                continue
            # manual acquire/release at statement level
            expr = stmt.value if isinstance(stmt, ast.Expr) else None
            if isinstance(expr, ast.Call) and \
                    isinstance(expr.func, ast.Attribute):
                lock = self.fa.resolve(expr.func.value, self.cls)
                if lock is not None and expr.func.attr == "acquire":
                    self._acquired(local_held, lock, stmt.lineno)
                    local_held = local_held + [lock]
                    continue
                if lock is not None and expr.func.attr == "release":
                    local_held = [h for h in local_held
                                  if h.node != lock.node]
                    continue
            self._note_writes(stmt, local_held)
            # compound statements: recurse into their bodies, scan the
            # header expressions with the current held set
            sub_bodies = []
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    sub_bodies.append(sub)
            handlers = getattr(stmt, "handlers", None) or []
            for h in handlers:
                sub_bodies.append(h.body)
            if sub_bodies:
                for field, value in ast.iter_fields(stmt):
                    if field in ("body", "orelse", "finalbody",
                                 "handlers"):
                        continue
                    if isinstance(value, ast.AST):
                        self._scan_expr(value, local_held)
                for sub in sub_bodies:
                    self._walk_body(sub, local_held)
            else:
                self._scan_expr(stmt, local_held)


# ---------------------------------------------------------------------------
# pass 3: TH01 threads
# ---------------------------------------------------------------------------
def _is_thread_join(node: ast.Call) -> bool:
    """A ``.join(...)`` call that can plausibly be a Thread join: the
    receiver is a bare name or a self-attribute — NOT a dotted module
    path (``os.path.join``) and NOT a string literal (``", ".join``),
    which would otherwise grant any path-touching function a free pass
    on the leak rule."""
    if not (isinstance(node.func, ast.Attribute) and
            node.func.attr == "join"):
        return False
    chain = _attr_chain(node.func.value)
    return chain is not None and (
        "." not in chain or
        (chain.startswith("self.") and chain.count(".") == 1))


def _lint_threads(tree: ast.Module, fa: _FileAnalysis):
    # receivers of any plausible thread-join call in the module
    join_receivers: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _is_thread_join(node):
            chain = _attr_chain(node.func.value)
            join_receivers.add(chain.split(".")[-1])

    # functions containing each Thread() call, to scope heuristics
    def enclosing_functions(tree):
        out = {}
        stack = []

        def visit(node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.append(node)
                for c in ast.iter_child_nodes(node):
                    visit(c)
                stack.pop()
                return
            out[id(node)] = stack[-1] if stack else None
            for c in ast.iter_child_nodes(node):
                visit(c)
        visit(tree)
        return out

    owners = enclosing_functions(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or \
                _call_name(node.func).split(".")[-1] != "Thread":
            continue
        name = _call_name(node.func)
        if name not in ("Thread", "threading.Thread"):
            continue
        daemon_kw = next((kw for kw in node.keywords
                          if kw.arg == "daemon"), None)
        if daemon_kw is not None and not (
                isinstance(daemon_kw.value, ast.Constant) and
                daemon_kw.value.value is False):
            continue   # daemon=True or dynamic: not a leak shape
        owner = owners.get(id(node))
        scope = owner.name if owner is not None else "<module>"
        # joined heuristics: (a) any .join( in the enclosing function,
        # (b) a `<var>.daemon = True` assignment in the function
        joined = False
        if owner is not None:
            for sub in ast.walk(owner):
                if isinstance(sub, ast.Call) and _is_thread_join(sub):
                    joined = True
                elif isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        chain = _attr_chain(tgt)
                        if chain and chain.endswith(".daemon") and \
                                isinstance(sub.value, ast.Constant) and \
                                sub.value.value is True:
                            joined = True
        else:
            joined = bool(join_receivers)
        if not joined:
            tgt_kw = next((kw for kw in node.keywords
                           if kw.arg == "target"), None)
            tgt = _call_name(tgt_kw.value) if tgt_kw is not None and \
                isinstance(tgt_kw.value, (ast.Name, ast.Attribute)) \
                else "?"
            fa.findings.append(Finding(
                fa.path, node.lineno, "TH01", scope, f"target:{tgt}",
                "threading.Thread created non-daemon with no reachable "
                "join() — it outlives its owner and can wedge "
                "interpreter shutdown (pass daemon=True, or join it on "
                "the close/exit path)"))


# ---------------------------------------------------------------------------
# propagation + cycle detection
# ---------------------------------------------------------------------------
def _propagate_calls(fa: _FileAnalysis):
    """Transitive acquires through intra-class/module calls: while
    holding H, calling a method that (transitively) acquires M adds
    the edge H -> M at the call site."""
    # fixpoint of all_acquires = direct U callees'
    all_acq = {q: set(a) for q, a in fa.fn_acquires.items()}
    changed = True
    while changed:
        changed = False
        for q, callees in fa.fn_calls.items():
            acc = all_acq.setdefault(q, set())
            for c in callees:
                extra = all_acq.get(c)
                if extra and not extra <= acc:
                    acc |= extra
                    changed = True
    for held, callee, line, scope in fa.call_sites:
        for node in sorted(all_acq.get(callee, ())):
            for h in held:
                fa.edges.append(_Edge(h, node, fa.path, line,
                                      f"via call to {callee} in {scope}"))


def _lock_context(fa: _FileAnalysis) -> Set[str]:
    """Private methods (``Class._name``, not dunder) whose every
    intra-file call site executes with a lock held — directly, or from
    another lock-context method.  Writes inside them are effectively
    guarded, so LK03 must not call them bare (the ``_push_locked``-
    style 'caller holds the lock' helper convention)."""
    sites: Dict[str, List[Tuple[bool, str]]] = {}
    for held, callee, _line, scope in fa.call_sites:
        tail = callee.rsplit(".", 1)[-1]
        if tail.startswith("_") and not tail.startswith("__"):
            sites.setdefault(callee, []).append((bool(held), scope))
    ctx: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for callee, evs in sites.items():
            if callee in ctx:
                continue
            if evs and all(held or caller in ctx
                           for held, caller in evs):
                ctx.add(callee)
                changed = True
    return ctx


def _cycle_findings(edges: List[_Edge], kinds: Dict[str, str]
                    ) -> List[Finding]:
    """LK01 findings: self-loops on non-reentrant locks + every
    strongly-connected component of >= 2 lock nodes."""
    findings: List[Finding] = []
    graph: Dict[str, Dict[str, _Edge]] = {}
    for e in edges:
        if e.src == e.dst:
            if kinds.get(e.src) == "Lock":
                findings.append(Finding(
                    e.path, e.line, "LK01", "<graph>",
                    f"self:{e.src}",
                    f"non-reentrant lock '{e.src}' is (re)acquired on "
                    f"a path that already holds it ({e.via}) — "
                    "guaranteed self-deadlock the first time this path "
                    "executes"))
            continue
        graph.setdefault(e.src, {}).setdefault(e.dst, e)
        graph.setdefault(e.dst, {})

    # Tarjan SCC
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(graph.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(graph.get(w, ()))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)

    for scc in sccs:
        if len(scc) < 2:
            continue
        members = sorted(scc)
        # anchor the finding at the smallest-line participating edge
        anchor = min(
            (graph[s][d] for s in scc for d in graph.get(s, {})
             if d in scc and s != d),
            key=lambda e: (e.path, e.line))
        chain = " -> ".join(members + [members[0]])
        findings.append(Finding(
            anchor.path, anchor.line, "LK01", "<graph>", chain,
            f"lock-order cycle {chain}: this process acquires these "
            "locks in inconsistent orders "
            f"(one edge: {anchor.src} -> {anchor.dst}, {anchor.via}) — "
            "two threads interleaving the paths can deadlock; pick one "
            "global order or drop to a single lock"))
    return findings


def _lk03_findings(fa: _FileAnalysis,
                   writes: Dict[str, Dict[str, List[Tuple]]]
                   ) -> List[Finding]:
    findings = []
    for cls, attrs in writes.items():
        for attr, evs in attrs.items():
            guarded = [e for e in evs if e[0]]
            bare = [e for e in evs
                    if not e[0] and not e[1].endswith("__init__")]
            if guarded and bare:
                for _g, qual, line in bare:
                    findings.append(Finding(
                        fa.path, line, "LK03", qual,
                        f"{cls}.{attr}",
                        f"'self.{attr}' is written under a lock in "
                        f"{guarded[0][1]} (line {guarded[0][2]}) but "
                        "written bare here — either the lock is "
                        "unnecessary or this write races with the "
                        "guarded one"))
    return findings


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------
def analyze_source(src: str, path: str) -> _FileAnalysis:
    tree = ast.parse(src)
    # node ids are keyed on the FULL module path (dotted): basenames
    # collide across packages (three different `__init__.py` locks in
    # this tree alone), and merged nodes would fabricate LK01 cycles
    # between unrelated locks
    modbase = os.path.splitext(path)[0].replace(os.sep, ".")
    if "/" in modbase:
        modbase = modbase.replace("/", ".")
    fa = _FileAnalysis(path, modbase)
    _collect_locks(tree, fa)

    # enumerate analysis units: every function def, with class context
    units: List[Tuple[str, Optional[str], ast.AST]] = []

    def collect_units(node, cls: Optional[str], prefix: str):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                collect_units(child, child.name, child.name)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                qual = f"{prefix}.{child.name}" if prefix else child.name
                units.append((qual, cls, child))
                collect_units(child, cls, qual)
            else:
                collect_units(child, cls, prefix)

    collect_units(tree, None, "")

    writes_by_class: Dict[str, Dict[str, List[Tuple]]] = {}
    for qual, cls, fn in units:
        sink = writes_by_class.setdefault(cls, {}) if cls else {}
        _FnWalker(fa, qual, cls, fn, sink).walk()
    _propagate_calls(fa)
    ctx = _lock_context(fa)
    if ctx:   # bare writes inside caller-holds-the-lock helpers are
        #       guarded in every real execution
        for attrs in writes_by_class.values():
            for attr, evs in attrs.items():
                attrs[attr] = [(g or q in ctx, q, ln)
                               for g, q, ln in evs]
    fa.findings.extend(_lk03_findings(
        fa, {c: w for c, w in writes_by_class.items() if c}))
    _lint_threads(tree, fa)
    return fa


def lint_source(src: str, path: str) -> List[Finding]:
    """Single-file convenience: local findings + cycles within the
    file's own graph (the CLI aggregates graphs across files)."""
    fa = analyze_source(src, path)
    findings = fa.findings + _cycle_findings(fa.edges, fa.lock_kinds)
    return _assign_occurrences(findings)


def lint_paths(paths: List[str]) -> List[Finding]:
    findings: List[Finding] = []
    edges: List[_Edge] = []
    kinds: Dict[str, str] = {}
    for root in paths:
        if os.path.isfile(root):
            files = [root]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        for f in sorted(files):
            rel = os.path.relpath(f, REPO)
            try:
                with open(f, encoding="utf-8") as fh:
                    src = fh.read()
            except OSError as e:
                print(f"conc_lint: cannot read {rel}: {e}",
                      file=sys.stderr)
                continue
            try:
                fa = analyze_source(src, rel)
            except SyntaxError as e:
                findings.append(Finding(rel, e.lineno or 0, "SYN", "?",
                                        "syntax", f"syntax error: {e}"))
                continue
            findings.extend(fa.findings)
            edges.extend(fa.edges)
            kinds.update(fa.lock_kinds)
    findings.extend(_cycle_findings(edges, kinds))
    return _assign_occurrences(findings)


def load_baseline(path: str) -> Set[str]:
    """Baseline keys; a trailing ``# justification`` per line is
    stripped (and required by review policy).  Keys never contain
    ``#``, so the comment starts at the first `` #`` regardless of
    how many spaces precede it."""
    if not os.path.exists(path):
        return set()
    out = set()
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            out.add(line.split(" #", 1)[0].strip())
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "paddle_tpu")])
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true",
                    help="re-seed the suppression list from current "
                         "findings (review each, add a justification)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding (exit 1 if any)")
    args = ap.parse_args(argv)

    findings = lint_paths(args.paths)
    if args.write_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write("# conc_lint baseline — reviewed findings "
                    "suppressed in CI.\n"
                    "# Every entry MUST carry a trailing "
                    "'  # justification'.\n"
                    "# Regenerate (after review!) with: "
                    "python tools/conc_lint.py --write-baseline\n")
            for k in sorted({fi.key() for fi in findings}):
                f.write(k + "  # TODO: justify or fix\n")
        print(f"conc_lint: wrote {len(findings)} finding(s) to "
              f"{os.path.relpath(args.baseline, REPO)}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(args.baseline)
    new = [f for f in findings if f.key() not in baseline]
    for f in findings:
        tag = "" if f.key() in baseline else "  <-- NEW"
        print(f"{f!r}{tag}")
    print(f"conc_lint: {len(findings)} finding(s), "
          f"{len(findings) - len(new)} baseline-suppressed, "
          f"{len(new)} new")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""CI chaos gate: run a small supervised ``Model.fit`` under a FIXED
chaos spec — one injected checkpoint-write failure plus delayed store
RPCs — SIGKILL the worker mid-run, and assert that training completes
with the expected ``chaos.injected`` / ``ckpt.write_fail`` /
``launch.restarts`` counts.

This is the end-to-end fault-tolerance smoke: supervisor relaunch,
verified checkpoint resume, chaos determinism, and metrics accounting
all have to line up for it to pass.  Wired into tools/run_all_tests.sh.
"""
import json
import os
import subprocess
import sys
import tempfile
import textwrap

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHAOS_SPEC = "ckpt.write:fail@2;store.rpc:delay=0.02@2-3"

TRAINER = """
import json, os, signal
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.distributed import checkpoint as ckpt
from paddle_tpu.hapi.callbacks import Callback
from paddle_tpu.profiler import metrics

gen = int(os.environ.get("PADDLE_RESTART_GENERATION", "0"))
work = os.environ["CHAOS_GATE_DIR"]

paddle.seed(0)
net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.Tanh(),
                           paddle.nn.Linear(8, 1))
model = paddle.Model(net)
opt = paddle.optimizer.Adam(1e-2, parameters=net.parameters())
model.prepare(opt, paddle.nn.MSELoss())


class DS(paddle.io.Dataset):
    def __getitem__(self, i):
        import time
        time.sleep(0.02)
        rng = np.random.RandomState(i)
        x = rng.rand(4).astype("float32")
        return x, (x.sum(keepdims=True) * 0.5).astype("float32")

    def __len__(self):
        return 32           # batch 4 -> 8 global steps


class Killer(Callback):
    def on_train_batch_end(self, step, logs=None):
        if gen == 0 and step == 5:
            os.kill(os.getpid(), signal.SIGKILL)


ckptr = ckpt.AsyncCheckpointer(os.path.join(work, "ckpt"), max_to_keep=3)
model.fit(DS(), batch_size=4, epochs=1, verbose=0, shuffle=False,
          checkpointer=ckptr, callbacks=[Killer()])
ckptr.close()
snap = metrics.snapshot()
with open(os.path.join(work, "metrics.json"), "w") as f:
    json.dump({"gen": gen, **{k: v for k, v in snap.items()
                              if k.startswith(("chaos.", "ckpt.",
                                               "resilience."))}}, f)
"""


def main():
    work = tempfile.mkdtemp(prefix="chaos_gate_")
    trainer = os.path.join(work, "trainer.py")
    with open(trainer, "w") as f:
        f.write(textwrap.dedent(TRAINER))
    report = os.path.join(work, "report.json")
    env = dict(os.environ,
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PYTHONPATH=REPO,
               FLAGS_chaos_spec=CHAOS_SPEC,
               CHAOS_GATE_DIR=work,
               PADDLE_HEARTBEAT_INTERVAL="0.05",
               PADDLE_SUPERVISE_REPORT=report)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--supervise", "--nproc", "1", "--max_restarts", "2", trainer],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    if r.returncode != 0:
        print(r.stdout[-3000:], file=sys.stderr)
        print(r.stderr[-3000:], file=sys.stderr)
        raise SystemExit(f"chaos gate: supervised launch failed "
                         f"(rc={r.returncode})")

    rep = json.load(open(report))
    assert rep["kind"] == "done", rep
    assert rep["restarts"] == 1, \
        f"expected exactly 1 supervised relaunch, got {rep}"
    assert rep["restarts_metric"] == 1, rep

    snap = json.load(open(os.path.join(work, "metrics.json")))
    assert snap["gen"] == 1, snap               # the resumed generation
    # deterministic schedule: per process, the 2nd checkpoint commit
    # fails and store RPCs 2-3 are delayed
    assert snap.get("chaos.injected.ckpt.write") == 1, snap
    assert snap.get("ckpt.write_fail") == 1, snap
    assert snap.get("chaos.injected.store.rpc", 0) >= 1, snap
    assert snap.get("chaos.injected", 0) == \
        snap.get("chaos.injected.ckpt.write", 0) + \
        snap.get("chaos.injected.store.rpc", 0), snap
    print(f"chaos gate OK: restarts={rep['restarts']}, "
          f"injected={snap['chaos.injected']} "
          f"(ckpt.write={snap['chaos.injected.ckpt.write']}, "
          f"store.rpc={snap['chaos.injected.store.rpc']}), "
          f"write_fail={snap['ckpt.write_fail']}")


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# Full test suite INCLUDING the slow tier (multi-process launcher parity,
# elastic scale-in, 32-virtual-device all-axes dryrun, ...).  This is the
# artifact-path invocation — the default `pytest tests/ -q` auto-skips
# @pytest.mark.slow; CI-style runs must use this script so the hardest
# distributed tests actually gate.
set -euo pipefail
cd "$(dirname "$0")/.."
# Observability gate first: the profiler/metrics layer is what every perf
# number reports through, so a broken tracer fails the sweep in seconds
# instead of after the slow tier (the full run below includes it again).
python -m pytest tests/test_profiler.py -q
# Static-analysis gates: (1) the framework AST linter must stay clean
# against its baseline (tools/framework_lint_baseline.txt — new
# findings fail, pre-existing ones are suppressed explicitly); (2) the
# concurrency linter (conc-san static side): lock-order cycles,
# blocking-under-lock, bare writes to guarded attributes, and unjoined
# non-daemon threads — same baseline discipline, every suppressed
# finding carries a justification; (3) the verifier-on-golden-programs
# check — test_passes.py mutates the golden programs from
# test_static_graph.py and asserts every defect class is caught with
# the op and var named.
python tools/framework_lint.py
python tools/conc_lint.py
python -m pytest tests/test_passes.py -q
# Fault-tolerance chaos gate: a supervised Model.fit run under a fixed
# chaos spec (one injected checkpoint-write failure + delayed store
# RPCs) with a mid-run SIGKILL — must complete via verified-checkpoint
# resume with the expected chaos.injected/launch.restarts counts.
python tools/chaos_gate.py
# Elastic degrade-and-continue gate: (1) 4 supervised workers under
# --np 2:4, SIGKILL one mid-step — the gang must re-form at world 3 via
# a rendezvous round (exact restart/rendezvous counts, zero restart
# budget spent), resume from the newest intact checkpoint, reshard a
# DP-sharded tree 4->3 bit-exactly inside the degraded gang, and match
# an uninterrupted run's loss trajectory; (2) a host.slow chaos delay
# on one rank must trip the straggler detector at exactly
# FLAGS_straggler_patience strikes and — under --evict_stragglers —
# re-form the gang without that host.
python tools/elastic_gate.py
# Async-pipeline gate: device-prefetched Model.fit must be bit-exact vs
# the synchronous loop on a fixed-seed 20-step run, the prefetch queue
# must actually run ahead, a loader.worker chaos kill must be recovered
# with zero lost batches, and the steady-state loop must block on the
# lazy loss at most once per log_freq window.
python tools/pipeline_gate.py
# Serving gate: the InferenceEngine under concurrent synthetic clients
# with a fixed serve.request chaos spec — zero lost requests (bit-exact
# vs unbatched Predictor.run), exactly one injected failure, exact
# queue_full shed count at the admission bound, and total XLA compiles
# bounded by the shape-bucket count.
python tools/serving_gate.py
# Compile-amortization gate: the same fit + serving-engine load +
# generation session runs twice in two processes sharing one
# FLAGS_compile_cache_dir — the second run must perform ZERO fresh XLA
# compiles (every AOT site hits the content-addressed artifact store,
# jax's persistent cache gains no entries) and be bit-exact with the
# first across all three legs.
python tools/cache_gate.py
# Decode gate: the continuous-batching GenerationEngine under
# concurrent staggered clients with a fixed serve.request chaos spec —
# zero lost requests, every streamed sequence bit-identical to the
# sequential generate() reference, exactly one injected failure, and
# total XLA compiles bounded by the prompt-bucket count (+1 decode
# executable) — the per-token-retrace failure mode stays pinned shut.
python tools/decode_gate.py
# Paged gate (PR 11 serving-memory subsystem): the PagedGenerationEngine
# under staggered concurrent streams with a fixed kv.block_alloc chaos
# spec — zero lost requests, paged greedy/sampled streams bit-exact vs
# the contiguous references, exactly one typed kv_blocks shed, a pinned
# prefix-cache hit count on a repeated-system-prompt workload, pools
# drained to all-free (no leaked block refcounts), and compiles still
# bounded by the prompt buckets (block tables are data, never shape).
python tools/paged_gate.py
# Kernel gate (r10 conv-leg MFU work): fixed-seed 10-step ResNet18 fit
# fused vs unfused must stay loss-parity within tolerance (step 1 to
# float32 noise), a conv+bn+relu block must dispatch as ONE op with the
# flag on and exactly the 3-op composition with it off, the fused
# optimizer must match the per-leaf reference at 1e-6 with
# bit-deterministic param sha256s, and an int8 resnet artifact must
# load and serve through the InferenceEngine with top-1 agreement and
# compiles bounded by the bucket count.
python tools/kernel_gate.py
# Concurrency-sanitizer gate (conc-san runtime side): the serving,
# decode, and pipeline soaks re-run with FLAGS_lock_san=1 (plus a
# threaded-DataLoader + async-checkpoint loader soak that engages the
# io/ckpt locks the pipeline contract can't) — the instrumented locks
# must record zero acquisition-order cycles and zero holds over
# threshold across real concurrent traffic, every leg must clear its
# engagement floor, and every gate's own bit-exactness/chaos/
# compile-bound assertions must still hold with the sanitizer in the
# lock path.
python tools/conc_gate.py
# Fleet gate (ISSUE 13 serving fabric): a 2-replica fleet + failover
# router must survive a chaos-injected dispatch-hop kill (exactly 1
# injection, exactly 1 failover retry, 5/5 bit-exact), shed with typed
# 429+Retry-After at the in-flight bound (exact count), hot-swap a new
# sha256-verified checkpoint mid-traffic via canary-then-promote with
# zero dropped streams and served bytes flipped to the new weights,
# re-spread a SIGKILLed replica's traffic with zero lost requests
# (SSE splice, bit-exact vs single-engine references), and drain the
# survivor cleanly — replica.join/leave + swap.* flight events at
# exact counts.
python tools/fleet_gate.py
# Observability gate (request tracing / fleet rollup / flight recorder):
# a traced HTTP generation request must echo its traceparent trace_id
# and export a complete ingress->admission->queue->prefill->decode->
# egress span chain, bit-stable across two fresh processes, with
# zero-cost pinned when tracing is off; a supervised 2-rank fit must
# serve BOTH ranks' labeled series from the supervisor's aggregated
# /metrics, merge per-rank chrome traces into one lane per rank, and a
# SIGKILLed rank must leave flight-recorder dumps (survivor + supervisor)
# whose tails carry the chaos/rendezvous events at exact counts.
python tools/obs_gate.py
# PS gate (ISSUE 15 fault-tolerant sharded embedding PS): a 2-shard
# CTR-tower training run over primary+replica pairs must survive a
# chaos-injected pull reset (exactly 1 injection riding the bounded
# transient retry) AND a real SIGKILL of a primary-shard subprocess
# (exactly 1 failover + 1 promotion, 2 counted retries) with the loss
# trajectory and final embedding rows bit-exact vs an uninterrupted
# reference (zero lost updates), winding down leak-free; and a table
# checkpointed at 4 shards (verified manifest-v2 commits) must reload
# onto 2 servers with row-union parity (no dup/drop, per-row
# bit-exact).
python tools/ps_gate.py
# Memory-accounting gate (ISSUE 16 memscope layer): a Model.fit with
# chaos-delayed checkpoint writes must decompose its wall-clock into
# goodput fractions summing to 1 (the delays charged to the checkpoint
# bucket, the first-step compile in the ledger, exact chaos counts in
# the flight ring, the goodput doc exported to PADDLE_FLIGHT_DIR) with
# zero-cost pinned when FLAGS_mem_accounting is off; a paged engine on
# a deliberately tiny block pool must turn the typed kv_blocks shed
# into an oom.r0.g0.json forensics artifact carrying the tagged
# census, pool/prefix-cache occupancy, and the flight tail with its
# mem.oom event.
python tools/mem_gate.py
# Static-analysis gate (ISSUE 17 planner/remat/amp-lint layer): the
# golden GPT + resnet18 eval captures must plan within +-15% of the
# memscope-measured replay peak, render through trace_summary
# --memplan, and lint AMP-clean; a remat-friendly tanh-chain train
# program rewritten under FLAGS_remat_budget_mb must keep loss and
# input-gradient bit-exact through the Executor while the MEASURED
# replay peak strictly drops.
python tools/memplan_gate.py
# AMP train-step gate (ISSUE 20 bf16/fp16 layer): a seeded MLP trains
# 10 steps fp32 vs O1/O2-bf16 through the jitted Model step with
# per-step loss parity inside the documented bf16 tolerance, zero new
# compiles on a warm rerun and the scaler never engaged for bf16; an
# fp16 run with dynamic loss scaling must skip the update bit-exactly
# on an inf-poisoned batch (scale halved, params untouched) and
# recover on the next clean batch; the auto_cast-captured train
# program must lint AMP-clean while a bf16-narrowed black-list op
# trips AMP01.
python tools/amp_gate.py
# Multi-tenant SLO gate (ISSUE 18 admission/preemption layer): with a
# 3-block pool saturated by batch-priority streams, every interactive
# burst must preempt the batch victim to pinned host memory and hand
# the pool back — exact serve.preempt/serve.resume flight counts, the
# preempted streams (greedy AND sampled) resuming bit-identical to
# their unpreempted references, interactive p99 bounded, zero lost
# requests, and the pool drained to all-free after close.
python tools/slo_gate.py
# Disaggregated prefill/decode gate (ISSUE 19 disagg layer): a
# 1-prefill + 2-decode fleet behind the prefix-aware router serves a
# shared-prompt workload with every stream (greedy AND sampled, JSON
# and SSE) bit-exact vs a monolithic reference engine, KV chains
# actually adopted over the wire, the fleet-wide prefix hit rate at
# the single-replica level, one injected kv.transfer failure ridden
# out by local re-prefill with zero lost requests, and every replica
# pool drained to all-free on SIGTERM.
python tools/disagg_gate.py
exec python -m pytest tests/ -q --runslow "$@"

class Model:
    pass
def summary(*a, **k):
    raise NotImplementedError
def flops(*a, **k):
    raise NotImplementedError

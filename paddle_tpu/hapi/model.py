"""paddle.Model — the high-level train/eval/predict API.

Reference parity: ``python/paddle/hapi/model.py:906`` (Model, fit:1556,
train_batch:1044, Dynamic/StaticGraphAdapter).  TPU-first: instead of two
adapters, Model has two execution engines:

- **eager**: per-op dispatch with tape autograd (debuggable), and
- **compiled** (default): ONE jitted XLA train-step threading
  (params, buffers, opt-state, rng) functionally — this is where MXU
  utilization comes from.  The compiled step is built once per input
  signature, mirrorring StaticGraphAdapter's lazily-built Program.
"""
from __future__ import annotations

import os
import pickle
import time
import warnings
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import autograd
from ..core.random import default_generator, rng_scope
from ..core.tensor import Tensor, to_tensor
from ..metric import Metric
from ..profiler import memscope as _memscope
from ..profiler import metrics as _metrics
from ..profiler import tracer as _obs
from ..utils import chaos as _chaos
from .callbacks import config_callbacks

__all__ = ["Model"]


import numbers


class _LazyScalar(numbers.Real):
    """Float-like view of a device scalar that materialises on first use.

    The reference's DygraphAdapter.train_batch calls ``loss.numpy()``
    eagerly — a ~µs sync on a locally attached GPU.  On TPU (and
    especially through a remote runtime) an eager per-step fetch stalls
    the whole async dispatch pipeline: profiled r4, ResNet50
    ``Model.train_batch`` spent ~100 ms/step blocked on the loss fetch
    against ~112 ms of device compute.  Keeping the scalar lazy lets
    consecutive steps pipeline; printing/comparing/formatting the loss
    coerces it via ``__float__`` exactly like a float.  (For JSON
    serialization, coerce explicitly: ``float(logs["loss"])``.)

    **Deferred-error contract**: a device fault in the step (or an
    XLA runtime error) surfaces at the first coercion of this scalar —
    potentially lines away from the ``train_batch`` call that queued the
    step.  Every coercion failure is re-raised annotated with the step
    index that produced the value, so the failing batch is always
    attributable.  For eager per-step surfacing (and NaN/Inf loss
    detection at the producing step), enable ``FLAGS_check_nan_inf`` —
    ``train_batch`` then materialises the loss before returning, at the
    documented pipeline cost.
    """

    __slots__ = ("_arr", "_val", "_origin")

    def __init__(self, arr, origin: str = None):
        self._arr = arr
        self._val = None
        self._origin = origin

    def __float__(self):
        if self._val is None:
            # each materialization is one host<->device round trip that
            # drains the async pipeline; counted so CI can assert the
            # steady-state loop blocks at most once per log_freq window
            _metrics.counter(
                "train.loss_fetch",
                "lazy-loss device scalars materialized on the host "
                "(each one is a pipeline sync point)").inc()
            try:
                self._val = float(self._arr)
            except Exception as e:
                raise RuntimeError(
                    f"device computation for {self._origin or 'this value'}"
                    f" failed; the error belongs to that step, not the "
                    f"line coercing the value (lazy-loss contract — see "
                    f"Model.train_batch)") from e
            self._arr = None
        return self._val

    def __repr__(self):
        return repr(float(self))

    def __format__(self, spec):
        return format(float(self), spec)

    def __bool__(self):
        return bool(float(self))

    def __int__(self):
        return int(float(self))

    def __index__(self):
        return int(float(self))

    def __array__(self, dtype=None, copy=None):
        return np.asarray(float(self), dtype=dtype or np.float64)

    def __hash__(self):
        return hash(float(self))

    # numbers.Real protocol — everything coerces through float()
    def __abs__(self): return abs(float(self))
    def __neg__(self): return -float(self)
    def __pos__(self): return float(self)
    def __trunc__(self): return float(self).__trunc__()
    def __floor__(self): return float(self).__floor__()
    def __ceil__(self): return float(self).__ceil__()
    def __round__(self, n=None): return round(float(self), n)
    def __add__(self, o): return float(self) + o
    def __radd__(self, o): return o + float(self)
    def __sub__(self, o): return float(self) - o
    def __rsub__(self, o): return o - float(self)
    def __mul__(self, o): return float(self) * o
    def __rmul__(self, o): return o * float(self)
    def __truediv__(self, o): return float(self) / o
    def __rtruediv__(self, o): return o / float(self)
    def __floordiv__(self, o): return float(self) // o
    def __rfloordiv__(self, o): return o // float(self)
    def __mod__(self, o): return float(self) % o
    def __rmod__(self, o): return o % float(self)
    def __pow__(self, o): return float(self) ** o
    def __rpow__(self, o): return o ** float(self)
    def __eq__(self, o): return float(self) == self._c(o)
    def __lt__(self, o): return float(self) < self._c(o)
    def __le__(self, o): return float(self) <= self._c(o)
    def __gt__(self, o): return float(self) > self._c(o)
    def __ge__(self, o): return float(self) >= self._c(o)

    @staticmethod
    def _c(o):
        return float(o) if isinstance(o, _LazyScalar) else o


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _batch_len(ins) -> int:
    """Samples in one batch (leading dim of the first input), 0 if moot."""
    try:
        return int(ins[0].shape[0])
    except Exception:
        return 0


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._use_jit = True
        self._jit_cache = {}
        self.stop_training = False
        self._save_dir = None

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit=True, offload=False):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle Metric")
        self._use_jit = jit
        self._amp_level = None
        self._amp_dtype = "bfloat16"
        self._amp_custom_white = None
        self._amp_custom_black = None
        self._amp_scaler_cfg = None
        self._amp_scaler_state = None
        if amp_configs:
            cfg = {"level": amp_configs} if isinstance(amp_configs, str) \
                else dict(amp_configs)
            self._amp_level = cfg.get("level", "O1")
            if self._amp_level not in ("O1", "O2"):
                raise ValueError(
                    f"amp_configs level must be 'O1' or 'O2', got "
                    f"{self._amp_level!r}")
            self._amp_dtype = cfg.get("dtype", "bfloat16")
            self._amp_custom_white = cfg.get("custom_white_list")
            self._amp_custom_black = cfg.get("custom_black_list")
            if self._amp_dtype in ("float16", "fp16"):
                # fp16's 5-bit exponent needs dynamic loss scaling; the
                # whole state machine rides INSIDE the jitted step (no
                # per-step host sync on found_inf).  bf16 shares fp32's
                # exponent range, so it never engages the scaler.
                self._amp_scaler_cfg = {
                    "init_loss_scaling": float(
                        cfg.get("init_loss_scaling", 2.0 ** 15)),
                    "incr_ratio": float(cfg.get("incr_ratio", 2.0)),
                    "decr_ratio": float(cfg.get("decr_ratio", 0.5)),
                    "incr_every_n_steps": int(
                        cfg.get("incr_every_n_steps", 1000)),
                    "decr_every_n_nan_or_inf": int(
                        cfg.get("decr_every_n_nan_or_inf", 2)),
                    "use_dynamic_loss_scaling": bool(
                        cfg.get("use_dynamic_loss_scaling", True)),
                }
        # opt-in optimizer-state offload to pinned host memory (the
        # single-device sibling of the ZeRO offload knob in
        # distributed/fleet/sharded_trainer.py) — trades one opt-state
        # round-trip of PCIe/host bandwidth per step for its HBM
        self._offload = bool(offload)
        return self

    # ------------------------------------------------------------------
    # compiled train step
    # ------------------------------------------------------------------
    def _build_jit_train_step(self, n_inputs, n_labels, remat=False):
        net, opt, loss_fn = self.network, self._optimizer, self._loss
        amp_level = self._amp_level
        amp_dtype = getattr(self, "_amp_dtype", "bfloat16")
        amp_white = getattr(self, "_amp_custom_white", None)
        amp_black = getattr(self, "_amp_custom_black", None)
        scaler_cfg = getattr(self, "_amp_scaler_cfg", None)
        low = None
        if amp_level:
            from ..core.dtype import dtype_to_jnp
            low = dtype_to_jnp(amp_dtype)

        def step(params, buffers, opt_state, scaler_state, key_base,
                 rng_ctr, lr, *data):
            # rng key derived IN-JIT from a device-resident counter
            # (same (seed, counter) stream as Generator.next_key): a
            # host-built key per step is a tiny host->device transfer
            # that serializes with the big execute on the axon tunnel —
            # measured ~14 ms/step of the ResNet50 wall time (r5)
            rng_ctr = rng_ctr + jnp.uint32(1)
            key = jnp.stack([key_base[0], key_base[1] ^ rng_ctr])
            inputs = [Tensor(a) for a in data[:n_inputs]]
            labels = [Tensor(a) for a in data[n_inputs:]]

            def loss_of(params):
                with rng_scope(key), autograd.no_grad():
                    if low is not None and amp_level == "O2":
                        # O2 master-weight contract: the fp32 params in
                        # `params` ARE the masters (the grad/update
                        # domain); the network sees a low-dtype view.
                        # The cast is inside the differentiated function,
                        # so grads land back on the fp32 leaves.
                        net_params = {
                            n: (p.astype(low) if p.dtype == jnp.float32
                                else p) for n, p in params.items()}
                    else:
                        net_params = params
                    net.load_functional_state(net_params, buffers)
                    if amp_level:
                        from ..amp import auto_cast
                        with auto_cast(level=amp_level, dtype=amp_dtype,
                                       custom_white_list=amp_white,
                                       custom_black_list=amp_black):
                            outs = net.forward(*inputs)
                    else:
                        outs = net.forward(*inputs)
                    outs_l = _to_list(outs)
                    loss = loss_fn(*(outs_l + labels))
                    new_buffers = {n: b._data for n, b in net.named_buffers()}
                loss_arr = loss._data if isinstance(loss, Tensor) else loss
                loss_arr = loss_arr.astype(jnp.float32)
                if scaler_state is not None:
                    loss_arr = loss_arr * scaler_state["scale"]
                return loss_arr, \
                    ([o._data for o in outs_l], new_buffers)

            if remat:
                # budget-driven rematerialization (FLAGS_remat_budget_mb
                # below the planner's peak estimate): keep matmul
                # outputs, recompute the cheap elementwise tail in the
                # backward — the same save-dots selection RematPass
                # makes over captured Programs
                loss_of = jax.checkpoint(
                    loss_of, policy=jax.checkpoint_policies.dots_saveable)

            (loss, (outs, new_buffers)), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)

            new_scaler = None
            if scaler_state is not None:
                # fp16 path: unscale, detect non-finite grads, and make
                # the whole update a per-leaf no-op on overflow — the
                # check_finite_and_unscale / update_loss_scaling state
                # machine fused into the step (zero host syncs)
                inv = jnp.float32(1.0) / scaler_state["scale"]
                loss = loss * inv
                found_inf = jnp.zeros((), jnp.bool_)
                for g in jax.tree_util.tree_leaves(grads):
                    found_inf = jnp.logical_or(
                        found_inf,
                        jnp.logical_not(jnp.all(jnp.isfinite(g))))
                grads = jax.tree_util.tree_map(
                    lambda g: (g * inv.astype(g.dtype)), grads)
                new_params, new_opt_state = opt.functional_apply(
                    params, grads, opt_state, lr)
                skip = jax.tree_util.tree_map(
                    lambda new, old: jnp.where(found_inf, old, new),
                    (new_params, new_opt_state), (params, opt_state))
                new_params, new_opt_state = skip
                if scaler_cfg["use_dynamic_loss_scaling"]:
                    from ..ops.amp_ops import update_loss_scaling
                    ns, ng, nb = update_loss_scaling(
                        Tensor(found_inf), Tensor(scaler_state["scale"]),
                        Tensor(scaler_state["good"]),
                        Tensor(scaler_state["bad"]),
                        scaler_cfg["incr_every_n_steps"],
                        scaler_cfg["decr_every_n_nan_or_inf"],
                        scaler_cfg["incr_ratio"], scaler_cfg["decr_ratio"])
                    new_scaler = {"scale": ns._data, "good": ng._data,
                                  "bad": nb._data, "found_inf": found_inf}
                else:
                    new_scaler = {"scale": scaler_state["scale"],
                                  "good": scaler_state["good"],
                                  "bad": scaler_state["bad"],
                                  "found_inf": found_inf}
            else:
                new_params, new_opt_state = opt.functional_apply(
                    params, grads, opt_state, lr)
            return loss, outs, new_buffers, new_params, new_opt_state, \
                new_scaler, rng_ctr

        return jax.jit(step, donate_argnums=(0, 2))

    def _remat_decision(self, batch_size: int = 1) -> bool:
        """True when ``FLAGS_program_remat`` + ``FLAGS_remat_budget_mb``
        are set and the static memory planner's train-peak estimate for
        this model exceeds the budget — the same flag pair that rewrites
        captured Programs (static/program.py pass pipeline), here
        deciding whether the jitted hapi step wraps its loss in
        :func:`jax.checkpoint`.  An un-plannable model (no input/label
        specs, capture failure) under an explicit budget remats
        conservatively.  The verdict is cached per budget value."""
        from ..utils import flags as _flags
        if not _flags.get_flag("FLAGS_program_remat"):
            return False
        budget_mb = int(_flags.get_flag("FLAGS_remat_budget_mb") or 0)
        if budget_mb <= 0:
            return False
        cached = getattr(self, "_remat_cache", None)
        if cached is not None and cached[0] == (budget_mb, batch_size):
            return cached[1]
        peak = None
        try:
            if self._inputs and self._labels:
                peak = int(self.static_memory_plan(
                    "train", batch_size=max(1, batch_size)).peak_bytes)
        except Exception:   # noqa: BLE001 — unplannable nets still remat
            peak = None
        on = peak is None or peak > budget_mb * (1 << 20)
        if on:
            warnings.warn(
                f"fit: rematerialization engaged — planner peak "
                f"{'unknown' if peak is None else f'{peak}B'} vs budget "
                f"{budget_mb}MB (FLAGS_remat_budget_mb); the train step "
                f"recomputes non-matmul activations in the backward")
        self._remat_active = on
        self._remat_planned_peak = peak
        self._remat_cache = ((budget_mb, batch_size), on)
        return on

    def _offload_shardings(self):
        """(host, device) shardings for the ``prepare(offload=True)``
        opt-state knob, or None when it cannot apply: data-parallel
        wrappers keep their ZeRO offload (sharded_trainer), and a
        backend without a ``pinned_host`` memory space (CPU) warns once
        and trains un-offloaded."""
        cached = getattr(self, "_offload_sh_cache", "unset")
        if cached != "unset":
            return cached
        result = None
        if not hasattr(self.network, "shard_inputs"):
            dev = jax.devices()[0]
            try:
                kinds = {m.kind for m in dev.addressable_memories()}
            except Exception:   # noqa: BLE001 — old backend API
                kinds = set()
            if "pinned_host" in kinds:
                from jax.sharding import SingleDeviceSharding
                result = (SingleDeviceSharding(
                              dev, memory_kind="pinned_host"),
                          SingleDeviceSharding(
                              dev, memory_kind="device"))
            else:
                warnings.warn(
                    "prepare(offload=True): this backend exposes no "
                    "pinned_host memory space — optimizer-state offload "
                    "is a no-op here (training proceeds un-offloaded)")
        else:
            warnings.warn(
                "prepare(offload=True): data-parallel models offload "
                "through the fleet ZeRO path (sharded_trainer offload=) "
                "— the hapi knob is a no-op under shard_inputs")
        self._offload_sh_cache = result
        return result

    def _device_rng_state(self):
        """(key_base, rng_ctr) device scalars for the jitted step,
        cached so the steady-state training loop does ZERO per-step
        host->device transfers.  Mirrors Generator.next_key's
        (splitmix64(seed), counter) stream exactly; resyncs whenever
        the host generator moved independently (reseed, eager draws,
        set_rng_state) and falls back to None in split-chain mode."""
        from ..core.random import counter_stream_key_words, _state
        gen = default_generator
        if gen._key is not None or getattr(_state, "scope", None) \
                is not None:
            # explicit-key mode, or an active rng_scope (which must
            # keep routing every draw): legacy per-step key path
            return None, None
        hi, lo = counter_stream_key_words(gen._seed)
        cache = getattr(self, "_rng_dev_cache", None)
        if cache is not None and cache[0] == (gen._seed, gen._counter):
            base, ctr = cache[1], cache[2]
        else:                          # first step / host moved: resync
            base = jnp.asarray(np.array([hi, lo], np.uint32))
            ctr = jnp.asarray(np.uint32(gen._counter))
        return base, ctr

    def _lr_device(self):
        lr_val = float(self._optimizer.get_lr())
        cache = getattr(self, "_lr_dev_cache", None)
        if cache is not None and cache[0] == lr_val:
            return cache[1]
        arr = jnp.asarray(lr_val, jnp.float32)
        self._lr_dev_cache = (lr_val, arr)
        return arr

    def _build_jit_eval_step(self, n_inputs, n_labels, with_loss):
        net, loss_fn = self.network, self._loss

        def step(params, buffers, *data):
            inputs = [Tensor(a) for a in data[:n_inputs]]
            labels = [Tensor(a) for a in data[n_inputs:]]
            with autograd.no_grad():
                net.load_functional_state(params, buffers)
                outs = _to_list(net.forward(*inputs))
                loss = None
                if with_loss and loss_fn is not None and labels:
                    l = loss_fn(*(outs + labels))
                    loss = (l._data if isinstance(l, Tensor) else l)
            return [o._data for o in outs], loss
        return jax.jit(step)

    # ------------------------------------------------------------------
    # batch-level API
    # ------------------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        self.network.train()
        if self._use_jit and update:
            return self._train_batch_jit(inputs, labels)
        return self._train_batch_eager(inputs, labels, update)

    def _train_batch_jit(self, inputs, labels):
        arrays = [to_tensor(t)._data for t in inputs + labels]
        if hasattr(self.network, "shard_inputs"):
            # DataParallel/hybrid wrapper: lay batch onto the mesh; XLA
            # then emits the cross-replica grad all-reduce (reducer.cc's
            # job in the reference) during compilation.
            arrays = self.network.shard_inputs(arrays)
        remat_on = self._remat_decision(batch_size=_batch_len(inputs))
        sig = ("train", remat_on,
               tuple((a.shape, str(a.dtype)) for a in arrays))
        step = self._jit_cache.get(sig)
        net, opt = self.network, self._optimizer
        params, buffers = net.functional_state()
        if not hasattr(opt, "_fn_state") or opt._fn_state is None:
            opt._fn_state = opt.functional_init(params)
        offload_sh = self._offload_shardings() \
            if getattr(self, "_offload", False) else None
        if offload_sh is not None and getattr(self, "_opt_on_host", False):
            # opt state parked in pinned host memory since last step:
            # stage it back into HBM for the (donating) jit step
            opt._fn_state = jax.device_put(opt._fn_state, offload_sh[1])
        scaler_state = None
        if getattr(self, "_amp_scaler_cfg", None) is not None:
            scaler_state = self._amp_scaler_state
            if scaler_state is None:
                c = self._amp_scaler_cfg
                scaler_state = {
                    "scale": jnp.asarray(c["init_loss_scaling"],
                                         jnp.float32),
                    "good": jnp.zeros((), jnp.int32),
                    "bad": jnp.zeros((), jnp.int32)}
        key_base, rng_ctr = self._device_rng_state()
        if key_base is None:
            # split-chain mode: a per-step host-built key (transfer) —
            # correctness over the zero-transfer fast path.  The step
            # derives key = [base0, base1 ^ (ctr+1)]; pre-XOR base1 so
            # the derived key equals the generator's key exactly
            key = default_generator.next_key()
            key_base = jnp.stack([key[0], key[1] ^ jnp.uint32(1)])
            rng_ctr = jnp.uint32(0)
            split_chain = True
        else:
            split_chain = False
        lr = self._lr_device()
        fresh_step = step is None
        aot_hit = False
        if step is None:
            step = self._build_jit_train_step(len(inputs), len(labels),
                                              remat=remat_on)
            from ..utils import artifact_store as _aot
            if _aot.active() is not None and \
                    not hasattr(self.network, "shard_inputs"):
                # single-device only: AOT executables are sharding-
                # strict, and the DP wrapper's param shardings evolve
                # between the first and later steps
                # AOT path through the artifact store: a relaunched
                # trainer (PR 3 supervisor) deserializes the persisted
                # executable instead of paying the XLA compile.  Any
                # lowering/serialization hiccup falls back to the plain
                # jit step — identical numerics either way.
                try:
                    step = _aot.aot_compile(
                        step.lower(params, buffers, opt._fn_state,
                                   scaler_state, key_base, rng_ctr,
                                   *([lr] + arrays)),
                        label="hapi.train_step")
                    aot_hit = True   # ledger entry recorded by the store
                except Exception:   # noqa: BLE001 — jit fallback
                    step = self._build_jit_train_step(len(inputs),
                                                      len(labels),
                                                      remat=remat_on)
            self._jit_cache[sig] = step
        # step-phase attribution: the dispatch call is where device
        # backpressure surfaces in a sync-free loop (XLA bounds the
        # in-flight queue), so its duration is the per-step "device"
        # phase; fit subtracts it from the body time to get "host"
        _d0 = _obs.now_ns() if _obs.active else 0
        # compile ledger: a fresh jit step compiles inside its first
        # dispatch; time that call so the ledger carries the wall cost
        # (the AOT path records its own entry through the store)
        _m0 = _obs.now_ns() if (_memscope.active and fresh_step
                                and not aot_hit) else 0
        try:
            (loss, outs, new_buffers, new_params, new_state, new_scaler,
             new_ctr) = step(params, buffers, opt._fn_state, scaler_state,
                             key_base, rng_ctr, *([lr] + arrays))
        except Exception as e:
            net.load_functional_state(params, buffers)  # drop leaked tracers
            if _memscope.active and _memscope.is_oom(e):
                # OOM forensics: census + flight ring land in
                # PADDLE_FLIGHT_DIR before the error re-raises
                _memscope.oom_dump(e, context="hapi.train_step")
            raise
        if _m0:
            _memscope.compile_record(
                "hapi.train_step", sig,
                (_obs.now_ns() - _m0) / 1e9, provenance="jit")
        if _d0:
            self._last_dispatch_ns = _obs.on_step_phase("device", _d0)
        if not split_chain:
            # mirror the in-jit counter bump on the host generator so
            # get_rng_state()/eager draws stay consistent, and keep the
            # device counter for the next step (zero transfers)
            default_generator._counter += 1
            self._rng_dev_cache = ((default_generator._seed,
                                    default_generator._counter),
                                   key_base, new_ctr)
        if new_scaler is not None:
            # device arrays, never synced here: found_inf is only
            # materialized if someone (tests, the nan guard) reads it
            self._amp_found_inf = new_scaler["found_inf"]
            self._amp_scaler_state = {k: new_scaler[k]
                                      for k in ("scale", "good", "bad")}
        if offload_sh is not None:
            new_state = jax.device_put(new_state, offload_sh[0])
            self._opt_on_host = True
            if _memscope.active:
                try:
                    _memscope.set_tag_bytes(
                        "host_offload", _memscope.tree_nbytes(new_state))
                except Exception:   # noqa: BLE001 — accounting never throws
                    pass
        opt._fn_state = new_state
        net.load_functional_state(new_params, new_buffers)
        if opt._lr_scheduler is None and hasattr(opt, "_global_step"):
            opt._global_step += 1
        metrics = self._update_metrics(outs, labels)
        self._train_step_count = getattr(self, "_train_step_count", 0) + 1
        lazy = _LazyScalar(loss,
                           origin=f"train step {self._train_step_count}")
        if _chaos.active and _chaos.hit("step.loss") == "nan":
            # chaos layer: poison this step's loss so the anomaly guard
            # / nan-check paths can be exercised deterministically
            lazy = float("nan")
        from ..utils import flags as _flags
        if _flags.get_flag("FLAGS_check_nan_inf"):
            # numeric-guard mode: surface device faults and NaN/Inf loss
            # AT the producing step (trades away the async pipeline)
            v = float(lazy)
            if not np.isfinite(v):
                raise FloatingPointError(
                    f"loss is {v} at train step {self._train_step_count} "
                    f"(FLAGS_check_nan_inf enabled)")
        return self._pack_logs(lazy, metrics)

    def _train_batch_eager(self, inputs, labels, update=True):
        net, opt = self.network, self._optimizer
        if self._amp_level:
            from ..amp import auto_cast
            with auto_cast(level=self._amp_level,
                           dtype=getattr(self, "_amp_dtype", "bfloat16"),
                           custom_white_list=getattr(
                               self, "_amp_custom_white", None),
                           custom_black_list=getattr(
                               self, "_amp_custom_black", None)):
                outs = _to_list(net(*[to_tensor(i) for i in inputs]))
        else:
            outs = _to_list(net(*[to_tensor(i) for i in inputs]))
        losses = self._loss(*(outs + [to_tensor(l) for l in labels]))
        losses.backward()
        if update:
            opt.step()
            opt.clear_grad()
        metrics = self._update_metrics([o._data for o in outs], labels)
        return self._pack_logs(float(losses), metrics)

    def eval_batch(self, inputs, labels=None):
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        self.network.eval()
        arrays = [to_tensor(t)._data for t in inputs + labels]
        if hasattr(self.network, "shard_inputs"):
            arrays = self.network.shard_inputs(arrays)
        sig = ("eval", tuple((a.shape, str(a.dtype)) for a in arrays))
        if sig not in self._jit_cache:
            self._jit_cache[sig] = self._build_jit_eval_step(
                len(inputs), len(labels), True)
        params, buffers = self.network.functional_state()
        try:
            outs, loss = self._jit_cache[sig](params, buffers, *arrays)
        finally:
            # tracing rebinds layer tensors to tracers; restore concrete
            self.network.load_functional_state(params, buffers)
        metrics = self._update_metrics(outs, labels)
        loss_val = float(loss) if loss is not None else None
        return self._pack_logs(loss_val, metrics)

    def predict_batch(self, inputs):
        inputs = _to_list(inputs)
        self.network.eval()
        arrays = [to_tensor(t)._data for t in inputs]
        if hasattr(self.network, "shard_inputs"):
            arrays = self.network.shard_inputs(arrays)
        sig = ("pred", tuple((a.shape, str(a.dtype)) for a in arrays))
        if sig not in self._jit_cache:
            self._jit_cache[sig] = self._build_jit_eval_step(
                len(inputs), 0, False)
        params, buffers = self.network.functional_state()
        try:
            outs, _ = self._jit_cache[sig](params, buffers, *arrays)
        finally:
            self.network.load_functional_state(params, buffers)
        return [np.asarray(o) for o in outs]

    def _update_metrics(self, out_arrays, labels):
        if not self._metrics:
            return {}
        results = {}
        for metric in self._metrics:
            computed = metric.compute(
                Tensor(out_arrays[0]), *[to_tensor(l) for l in labels])
            if isinstance(computed, (list, tuple)):
                res = metric.update(*[c for c in computed])
            else:
                res = metric.update(computed)
            names = metric.name()
            results[names[0] if isinstance(names, list) else names] = res
        return results

    def _pack_logs(self, loss, metrics):
        logs = {}
        if loss is not None:
            logs["loss"] = loss
        logs.update(metrics)
        return logs

    # ------------------------------------------------------------------
    # fault tolerance: checkpoint resume, heartbeats, anomaly guard
    # ------------------------------------------------------------------
    def _ckpt_tree(self, step_count: int):
        """(params, buffers, opt, rng, step) as one checkpointable tree —
        everything a relaunched worker needs to continue bit-exactly.
        The meta leaf also records the data-parallel world and the
        exact global sample count consumed, so a relaunch at a
        DIFFERENT world size (elastic shrink/grow) can recompute its
        replay offset by samples instead of by now-meaningless step
        indices."""
        params, buffers = self.network.functional_state()
        opt = self._optimizer
        if getattr(opt, "_fn_state", None) is None:
            opt._fn_state = opt.functional_init(params)
        gen = default_generator
        return {"params": params, "buffers": buffers,
                "opt": opt._fn_state,
                "meta": {"step": np.int64(step_count),
                         "rng_seed": np.uint64(gen._seed),
                         "rng_counter": np.uint64(gen._counter),
                         "world": np.int64(
                             getattr(self, "_fit_data_world", 1)),
                         "samples": np.int64(
                             getattr(self, "_fit_samples_seen", 0)),
                         # epoch-scoped counters: cross-world replay
                         # must not compare sample totals ACROSS epochs
                         # (DistributedBatchSampler ceil-pads each
                         # epoch to a world-dependent total)
                         "epoch": np.int64(
                             getattr(self, "_fit_epoch", 0)),
                         "samples_epoch": np.int64(
                             getattr(self, "_fit_samples_epoch", 0))}}

    def _fit_resume(self, checkpointer, data_world: Optional[int] = None):
        """Restore the newest intact checkpoint (corrupt steps are
        quarantined by the checkpointer); returns a dict of
        ``{"step", "world", "samples"}`` describing the restored state,
        or None when nothing intact exists (cold start — the live state
        is left untouched).  ``world``/``samples`` are None for trees
        written before manifest v2 (which still load via the legacy
        meta template)."""
        from ..distributed.checkpoint import (CheckpointCorruptError,
                                              derive_rank_seed)
        if data_world is None:
            data_world = getattr(self, "_fit_data_world", 1)
        template = self._ckpt_tree(0)
        legacy = dict(template)
        legacy["meta"] = {k: template["meta"][k]
                          for k in ("step", "rng_seed", "rng_counter")}
        try:
            restored = checkpointer.restore(template=template)
        except CheckpointCorruptError:
            if checkpointer.all_steps():
                warnings.warn(
                    "fit: no intact checkpoint survived verification; "
                    "starting from scratch")
            return None
        except Exception:
            # the manifest format decides whether this is a pre-v2 tree
            # (whose meta lacks the new keys, so the full template
            # mismatches the stored structure) or a v2 tree that failed
            # for a real reason — only the former gets the legacy-shape
            # retry (MIGRATION: v1 trees still load, sans cross-world
            # replay recompute); masking a genuine v2 failure behind a
            # legacy retry would bury the actual error
            seen = getattr(checkpointer, "last_restored_meta", None) or {}
            if int(seen.get("format") or 1) >= 2:
                raise
            try:
                restored = checkpointer.restore(template=legacy)
            except CheckpointCorruptError:
                if checkpointer.all_steps():
                    warnings.warn(
                        "fit: no intact checkpoint survived "
                        "verification; starting from scratch")
                return None
        self.network.load_functional_state(restored["params"],
                                           restored["buffers"])
        self._optimizer._fn_state = restored["opt"]
        meta = restored["meta"]
        old_world = int(meta["world"]) if "world" in meta else None
        samples = int(meta["samples"]) if "samples" in meta else None
        epoch = int(meta["epoch"]) if "epoch" in meta else 0
        samples_epoch = int(meta["samples_epoch"]) \
            if "samples_epoch" in meta else samples
        gen = default_generator
        if old_world is not None and old_world != data_world:
            # cross-world resume: the rank<->host mapping has rotated,
            # so each survivor re-derives its stream deterministically
            # from its NEW rank instead of inheriting whichever old
            # rank's stream happens to be in the restored tree
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            gen._seed = derive_rank_seed(int(meta["rng_seed"]), rank)
        else:
            gen._seed = int(meta["rng_seed"])
        gen._counter = int(meta["rng_counter"])
        gen._key = None
        self._rng_dev_cache = None     # device counter resyncs next step
        step = int(meta["step"])
        warnings.warn(f"fit: resumed from checkpoint at step {step} "
                      f"(generation "
                      f"{os.environ.get('PADDLE_RESTART_GENERATION', '0')}"
                      + (f", saved at data-parallel world {old_world}"
                         if old_world is not None else "") + ")")
        # the DIRECTORY label the checkpointer restored from: may sit
        # above meta["step"] when an earlier elastic resume offset the
        # numbering (see _fit_save_offset in fit)
        seen = getattr(checkpointer, "last_restored_meta", None) or {}
        label = seen.get("step")
        label = step if label is None else int(label)
        return {"step": step, "world": old_world, "samples": samples,
                "epoch": epoch, "samples_epoch": samples_epoch,
                "label": label}

    def _make_heartbeat(self):
        """Supervised-launch heartbeat: when the launcher exported
        PADDLE_SUPERVISE_STORE, put this rank's step payload under the
        generation-prefixed supervise key so the watchdog can tell
        progress from a hang — and, since the payload carries the mean
        per-step wall time between beats, so the supervisor's straggler
        detector can median step times across the gang.  The generation
        prefix keeps a slow-dying worker from a prior generation from
        feeding the current generation's watchdog.  Returns None (zero
        per-step cost) when unsupervised."""
        spec = os.environ.get("PADDLE_SUPERVISE_STORE")
        if not spec:
            return None
        import json as _json
        # supervised workers also install the SIGUSR1 thread-dump
        # handler: before the watchdog kills a stalled gang it signals
        # each worker, so the wedged one's log ends with every thread's
        # stack and currently-held sanitizer locks (diagnosable
        # artifact instead of a silent SIGKILL)
        from ..utils import concurrency as _conc
        _conc.install_signal_dump()
        from ..distributed import fleet_metrics as _fleet
        from ..distributed.fleet.elastic.manager import store_from_spec
        from ..distributed.launch import heartbeat_key
        from ..profiler import flight as _flight
        store = store_from_spec(spec)
        job = os.environ.get("PADDLE_SUPERVISE_JOB", "default")
        gen = os.environ.get("PADDLE_RESTART_GENERATION", "0")
        rank = os.environ.get("PADDLE_TRAINER_ID", "0")
        key = heartbeat_key(job, gen, rank)
        interval = float(os.environ.get("PADDLE_HEARTBEAT_INTERVAL",
                                        "1.0"))
        if _flight.active:
            _flight.note("launch", "fit_start", generation=gen,
                         rank=rank,
                         world=os.environ.get("PADDLE_TRAINERS_NUM"))
        state = {"t": 0.0, "step": None}

        def beat(step):
            now = time.monotonic()
            if now - state["t"] < interval:
                return
            payload = {"step": step}
            prev_t, prev_step = state["t"], state["step"]
            if prev_t and isinstance(step, int) and \
                    isinstance(prev_step, int) and step > prev_step:
                # mean per-step wall time since the last beat — the
                # straggler detector's input (int steps only: eval
                # beats keep the watchdog fed but carry no timing)
                payload["dt"] = round((now - prev_t) /
                                      (step - prev_step), 6)
            state["t"], state["step"] = now, step
            try:
                store.put(key, _json.dumps(payload))
            except Exception:
                pass   # store blip: the TTL/watchdog slack absorbs it
            try:
                # fleet metrics ride the heartbeat cadence: one registry
                # snapshot per beat under a generation-prefixed key the
                # supervisor aggregates into its /metrics endpoint
                _fleet.publish(store, job, gen, rank, step=step)
            except Exception:
                pass   # same store-blip tolerance as the beat itself

        return beat

    def _state_refs(self):
        # deep copies, not refs: the jitted step DONATES params/opt
        # buffers (donate_argnums), so the pre-step arrays are dead the
        # moment the step runs — reverting must restore surviving copies
        def cp(a):
            return jnp.array(a._data if hasattr(a, "_data") else a,
                             copy=True)
        params, buffers = self.network.functional_state()
        opt_state = getattr(self._optimizer, "_fn_state", None)
        return (jax.tree.map(cp, params), jax.tree.map(cp, buffers),
                None if opt_state is None else jax.tree.map(cp, opt_state))

    def _restore_state_refs(self, snap):
        params, buffers, opt_state = snap
        self.network.load_functional_state(params, buffers)
        if opt_state is not None:
            self._optimizer._fn_state = opt_state

    def _handle_anomaly(self, action, value, step_count, snap,
                        checkpointer):
        """nan/inf loss policy (FLAGS_anomaly_action).  'skip' reverts
        this step's update; 'rollback' restores the newest intact
        checkpoint (data is not rewound — training continues with the
        next batch either way)."""
        from ..profiler import flight as _flight
        from ..profiler import metrics as _metrics
        _metrics.counter("train.anomaly",
                         "nan/inf losses caught by the fit anomaly "
                         "guard").inc()
        if _flight.active:
            _flight.note("train", "anomaly", value=str(value),
                         step=step_count, action=action)
        if action == "raise":
            raise FloatingPointError(
                f"loss is {value} at train step {step_count} "
                f"(FLAGS_anomaly_action=raise)")
        if action == "rollback" and checkpointer is not None:
            restored = self._fit_resume(checkpointer)
            if restored is not None:
                warnings.warn(f"anomalous loss {value} at step "
                              f"{step_count}: rolled back to checkpoint "
                              f"step {restored['step']}")
                return
            warnings.warn("FLAGS_anomaly_action=rollback: no intact "
                          "checkpoint yet, reverting this step instead")
        elif action == "rollback":
            warnings.warn("FLAGS_anomaly_action=rollback without a "
                          "checkpointer: reverting this step instead")
        self._restore_state_refs(snap)
        # the eager/accumulation path has already backward()ed the
        # poisoned loss into .grad — flush it or the next boundary
        # opt.step() applies the NaN update anyway (no-op on the
        # functional jit path, which carries no .grad state)
        if hasattr(self._optimizer, "clear_grad"):
            self._optimizer.clear_grad()
        warnings.warn(f"anomalous loss {value} at step {step_count}: "
                      f"step reverted, continuing")

    # ------------------------------------------------------------------
    # loop-level API
    # ------------------------------------------------------------------
    def _epoch_input(self, loader, depth):
        """(iterator, prefetcher-or-None) for one epoch over ``loader``:
        the io DevicePrefetcher stage (background collate +
        ``device_put``, ``depth`` batches resident on device) unless
        disabled or the loader runs its own.  For DataParallel/hybrid
        networks the prefetch ``device_put`` uses the step's input
        sharding, so multi-chip feeds land pre-sharded; on meshes with
        no local placement (multi-host) prefetch is bypassed entirely —
        including a loader-owned stage — because batches must stay
        host-side for the in-step global sharding."""
        from ..io import DataLoader, DevicePrefetcher
        from ..utils import flags as _flags
        if depth is None:
            depth = _flags.get_flag("FLAGS_prefetch_to_device")
        depth = int(depth or 0)
        sharding = None
        dp_net = self._use_jit and hasattr(self.network, "shard_inputs") \
            and getattr(self.network, "mesh", None) is not None
        if dp_net:
            from ..distributed.parallel import input_sharding_fn
            sharding = input_sharding_fn(
                self.network.mesh, getattr(self.network, "_dp_axis", "dp"))
            if sharding is None:
                # no local placement exists: force host batches even if
                # the loader has its own device-prefetch stage
                if getattr(loader, "prefetch_to_device", 0) > 0 and \
                        hasattr(loader, "_iter_batches"):
                    return loader._iter_batches(), None
                return iter(loader), None
        if getattr(loader, "prefetch_to_device", 0) > 0:
            # the loader's own stage runs in its __iter__; (re)hand it
            # this fit's input sharding — a loader reused across
            # models/meshes must not keep a stale closure
            loader._input_sharding = sharding
            return iter(loader), None
        if depth <= 0:
            return iter(loader), None
        if isinstance(loader, DataLoader):
            pf = DevicePrefetcher.for_loader(loader, depth=depth,
                                             sharding=sharding)
        else:
            try:
                pf = DevicePrefetcher(iter(loader), depth=depth,
                                      sharding=sharding)
            except TypeError:
                return iter(loader), None
        self._last_prefetcher = pf
        return iter(pf), pf

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None, checkpointer=None,
            prefetch_to_device=None):
        from ..io import DataLoader, Dataset
        self._save_dir = save_dir
        if isinstance(train_data, Dataset):
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if eval_data is not None and isinstance(eval_data, Dataset):
            eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                     num_workers=num_workers)
        else:
            eval_loader = eval_data

        try:
            steps = len(train_loader)
        except TypeError:
            steps = None
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                batch_size=batch_size, steps=steps,
                                log_freq=log_freq, verbose=verbose,
                                save_freq=save_freq, save_dir=save_dir,
                                metrics=["loss"] + [m.name() for m in
                                                    self._metrics])
        # fault-tolerance hooks: all of them cost one predicate read per
        # step when unconfigured (no supervisor env, no checkpointer, no
        # anomaly flag) — the PR-1 instrumentation discipline
        from ..utils import flags as _flags
        anomaly = _flags.get_flag("FLAGS_anomaly_action")
        heartbeat = self._heartbeat = self._make_heartbeat()
        # data-parallel world of the DATA pipeline: >1 only when the
        # loader actually shards the index space across ranks
        # (DistributedBatchSampler) — replicated-data gangs train every
        # sample on every rank, so their replay offsets are world-free
        from ..io import DistributedBatchSampler
        data_world = 1
        bs = getattr(train_loader, "batch_sampler", None)
        if isinstance(bs, DistributedBatchSampler):
            data_world = int(bs.nranks)
        self._fit_data_world = data_world
        self._fit_samples_seen = 0
        start_step = 0
        resume_samples = None
        resume_epoch = 0
        # checkpoint directory labels must stay monotonic across
        # elastic resumes: a GROW renumbers step_count DOWNWARD on the
        # new grid, and saving step 101 next to a stale old-world step
        # 200 would make every later restore pick the pre-grow tree.
        # The offset keeps labels strictly increasing while the tree's
        # meta keeps the true new-grid step count for replay math.
        self._fit_save_offset = 0
        if checkpointer is not None and self._optimizer is not None:
            info = self._fit_resume(checkpointer, data_world)
            if info is not None:
                if info["world"] is not None and \
                        info["world"] != data_world and \
                        info["samples_epoch"] is not None:
                    self._fit_save_offset = info["label"]
                    # elastic world change: step indices from the old
                    # world are meaningless here — replay completed
                    # epochs wholesale (their padded sample totals are
                    # world-dependent, so the counts don't transfer)
                    # and skip WITHIN the saved epoch by global sample
                    # count, so nothing is double-trained or silently
                    # dropped
                    resume_samples = info["samples_epoch"]
                    resume_epoch = info["epoch"]
                    warnings.warn(
                        f"fit: resharded resume — checkpoint was taken "
                        f"at data-parallel world {info['world']}, this "
                        f"run is world {data_world}; replaying "
                        f"{resume_epoch} completed epoch(s) plus "
                        f"{resume_samples} already-trained global "
                        f"samples instead of old-world step indices")
                else:
                    start_step = info["step"]
                    # same-world: continue an existing label offset
                    # (label == step means no offset ever applied)
                    self._fit_save_offset = max(
                        0, info["label"] - info["step"])

        cbks.on_train_begin()
        # memscope goodput/attribution layer: one predicate read per
        # hook when FLAGS_mem_accounting is off (`_gp is None` below)
        _gp = None
        _tagged_opt = False
        if _memscope.active:
            try:
                _memscope.set_tag_bytes(
                    "params",
                    _memscope.tree_nbytes(self.network.functional_state()))
            except Exception:   # noqa: BLE001 — accounting never throws
                pass
            _gp = _memscope.GoodputMeter("train").start()
        step_count = 0
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            self._fit_epoch = epoch
            self._fit_samples_epoch = 0
            for m in self._metrics:
                m.reset()
            logs = {}
            # async input pipeline: a fresh one-shot prefetch stage per
            # epoch; falls through to the plain loader when disabled
            it, pf = self._epoch_input(train_loader, prefetch_to_device)
            step = -1
            try:
                while True:
                    # step-phase breakdown (host tracer on): data_wait
                    # is the time this loop blocked on the input
                    # pipeline; with prefetch warm it is ~queue-pop
                    trace = _obs.active
                    _tw0 = _obs.now_ns() if (trace or _gp is not None) \
                        else 0
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    if _tw0:
                        _dw = _obs.on_step_phase("data_wait", _tw0) \
                            if trace else _obs.now_ns() - _tw0
                        if _gp is not None:
                            _gp.add_ns("data_wait", _dw)
                    step += 1
                    if resume_samples is not None:
                        # cross-world resume: replay the data order,
                        # counting GLOBAL samples (this rank's batch x
                        # data world) until the checkpoint's consumed-
                        # sample mark — the step grid of the old world
                        # doesn't exist here.  Completed old-world
                        # epochs replay WHOLESALE — their ceil-padded
                        # sample totals are world-dependent, so the
                        # counts don't transfer across epochs.
                        if epoch > resume_epoch:
                            # the saved epoch is exhausted on the new
                            # grid (its old padded total can exceed the
                            # new one); training resumes here
                            resume_samples = None
                        else:
                            bl = _batch_len(self._split_batch(batch)[0]) \
                                * data_world
                            if epoch < resume_epoch or \
                                    self._fit_samples_epoch + bl <= \
                                    resume_samples:
                                self._fit_samples_seen += bl
                                self._fit_samples_epoch += bl
                                step_count += 1
                                continue
                            if self._fit_samples_epoch < resume_samples:
                                warnings.warn(
                                    f"fit: resharded-resume boundary "
                                    f"falls inside a batch — re-training "
                                    f"{resume_samples - self._fit_samples_epoch}"
                                    f" of {resume_samples} replayed "
                                    f"samples (the old step boundary is "
                                    f"not representable on the new "
                                    f"world's batch grid)")
                            resume_samples = None
                    if resume_samples is None and \
                            step_count < start_step:
                        # resumed run: this batch's update is already
                        # inside the restored state — replay the data
                        # order without re-training (shuffle must be
                        # deterministic/off for exact continuation, as
                        # in the reference resume)
                        step_count += 1
                        bl = _batch_len(
                            self._split_batch(batch)[0]) * data_world
                        self._fit_samples_seen += bl
                        self._fit_samples_epoch += bl
                        continue
                    cbks.on_train_batch_begin(step)
                    ins, lbls = self._split_batch(batch)
                    # profiler v2 hot-path hook: with the host tracer
                    # off this whole block is one predicate read per
                    # step
                    _t0 = _obs.now_ns() if trace else 0
                    self._last_dispatch_ns = 0
                    if anomaly:
                        # pre-step copies (the jit step donates its
                        # inputs); this is the guard's per-step cost
                        snap = self._state_refs()
                    _s0 = _obs.now_ns() if _gp is not None else 0
                    if accumulate_grad_batches > 1:
                        # grad accumulation rides the eager tape:
                        # backward accumulates into .grad, step fires on
                        # the boundary
                        update = (step + 1) % accumulate_grad_batches == 0
                        self.network.train()
                        logs = self._train_batch_eager(ins, lbls,
                                                       update=update)
                    else:
                        logs = self.train_batch(ins, lbls)
                    if _s0:
                        _gp.step_ns(_obs.now_ns() - _s0)
                        if not trace:
                            # tracer off: the phase hooks don't run, so
                            # sample the step watermark here
                            _memscope.on_phase("step")
                        if not _tagged_opt:
                            _tagged_opt = True
                            st = getattr(self._optimizer, "_fn_state",
                                         None)
                            if st is not None:
                                _memscope.set_tag_bytes(
                                    "opt_state",
                                    _memscope.tree_nbytes(st))
                    if _t0:
                        _obs.on_hapi_step(_t0, num_samples=_batch_len(ins),
                                          mode="train")
                    step_count += 1
                    self._fit_samples_seen += _batch_len(ins) * data_world
                    self._fit_samples_epoch += _batch_len(ins) * data_world
                    if anomaly and "loss" in logs:
                        # guard mode materialises the loss at the
                        # producing step (its documented synchronous
                        # trade against the lazy-loss pipeline)
                        v = float(logs["loss"])
                        if not np.isfinite(v):
                            _a0 = _obs.now_ns() if _gp is not None else 0
                            self._handle_anomaly(anomaly, v, step_count,
                                                 snap, checkpointer)
                            if _a0:
                                _gp.add_ns("anomaly",
                                           _obs.now_ns() - _a0)
                            logs["loss"] = v
                    if _chaos.active:
                        # host.slow: deterministic per-rank slowdown of
                        # the step loop (a 'delay' action stretches
                        # this step's wall time, which the next beat's
                        # dt payload then reports — the straggler-
                        # detection test bed)
                        _chaos.hit("host.slow")
                    if heartbeat is not None:
                        # step + per-step wall time — never the device
                        heartbeat(step_count)
                    save_label = step_count + self._fit_save_offset
                    if checkpointer is not None and (
                            not hasattr(checkpointer, "want_save")
                            or checkpointer.want_save(save_label)):
                        # tree build + host snapshot only on steps the
                        # checkpointer will actually write; interval
                        # steps stay sync-free.  The directory label
                        # carries the elastic offset; the tree's meta
                        # records the true new-grid step count
                        _c0 = _obs.now_ns() if _gp is not None else 0
                        checkpointer.save(save_label,
                                          self._ckpt_tree(step_count))
                        if _c0:
                            _gp.add_ns("checkpoint",
                                       _obs.now_ns() - _c0)
                    # reference hapi: callbacks see the ACTUAL batch
                    # size so ips stays honest on the final partial
                    # batch
                    logs["batch_size"] = _batch_len(ins)
                    cbks.on_train_batch_end(step, logs)
                    if _t0:
                        _obs.on_step_host(
                            _obs.now_ns() - _t0 - self._last_dispatch_ns)
                    if num_iters is not None and step_count >= num_iters:
                        break
            finally:
                if pf is not None:
                    pf.close()
                else:
                    # a loader-owned stage must also stop promptly on an
                    # exception — a stored traceback pins the suspended
                    # generator and would keep its producer alive
                    lpf = getattr(train_loader, "_last_prefetcher", None)
                    if lpf is not None:
                        lpf.close()
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and epoch % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks,
                              _inner=True)
            if cbks.stop_training or self.stop_training:
                break
            if num_iters is not None and step_count >= num_iters:
                break
        cbks.on_train_end()
        if checkpointer is not None:
            # the final step's async write must land before fit returns
            # (a supervisor relaunch right after fit would otherwise
            # resume one step short) — goodput charges the drain to the
            # checkpoint bucket: a chaos-delayed ckpt.write stalls HERE
            _c0 = _obs.now_ns() if _gp is not None else 0
            checkpointer.wait_until_finished()
            if _c0:
                _gp.add_ns("checkpoint", _obs.now_ns() - _c0)
        if _gp is not None:
            # train.goodput.* gauges + the goodput.r<rank>.g<gen>.json
            # doc the PR 9 supervise report folds in
            self._last_goodput = _gp.finish(
                extra={"steps": step_count,
                       "samples": self._fit_samples_seen})

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            n_in = len(self._inputs) if self._inputs else 1
            if len(batch) <= n_in:
                return list(batch), []
            return list(batch[:n_in]), list(batch[n_in:])
        return [batch], []

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _inner=False):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = eval_data
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, verbose=verbose, log_freq=log_freq,
            mode="eval")
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin()
        losses = []
        logs = {}
        heartbeat = getattr(self, "_heartbeat", None)
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, lbls = self._split_batch(batch)
            if heartbeat is not None:
                # epoch-end evaluation advances the heartbeat too, so a
                # long eval pass isn't misread as a hung train step
                heartbeat(f"eval-{step}")
            _t0 = _obs.now_ns() if _obs.active else 0
            logs = self.eval_batch(ins, lbls)
            if _t0:
                _obs.on_hapi_step(_t0, num_samples=_batch_len(ins),
                                  mode="eval")
            if "loss" in logs:
                losses.append(logs["loss"])
            logs["batch_size"] = _batch_len(ins)
            cbks.on_eval_batch_end(step, logs)
        final = {}
        if losses:
            final["loss"] = float(np.mean(losses))
        for m in self._metrics:
            names = m.name()
            res = m.accumulate()
            final[names[0] if isinstance(names, list) else names] = res
        cbks.on_eval_end(final)
        return final

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs])
                    for i in range(n_out)]
        return outputs

    # ------------------------------------------------------------------
    # persistence / introspection
    # ------------------------------------------------------------------
    def save(self, path, training=True):
        from .. import framework_io
        if training:
            framework_io.save(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                framework_io.save(self._optimizer.state_dict(),
                                  path + ".pdopt")
        else:
            from .. import jit as jit_mod
            specs = None
            if self._inputs:
                specs = self._inputs
            jit_mod.save(self.network, path, input_spec=specs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import framework_io
        state = framework_io.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(framework_io.load(opt_path))

    def static_memory_plan(self, mode="train", input_spec=None,
                           label_spec=None, batch_size=1):
        """Capture this model as a static Program and return its
        :class:`~paddle_tpu.static.passes.memory_plan.MemoryPlan` — a
        byte-accurate peak-HBM estimate with a per-op liveness timeline,
        without allocating a single device buffer.

        ``mode="eval"`` plans the forward pass only; ``mode="train"``
        additionally runs :func:`static.append_backward` on the captured
        loss, so activations pinned as vjp residuals and parameter
        gradients are counted.  The train view covers forward+backward;
        optimizer update state (momentum/variance slots) is not part of
        the captured program, so the estimate undershoots a measured
        training peak by roughly one extra parameter-sized buffer per
        optimizer slot.

        Specs default to the ``inputs=``/``labels=`` the Model was
        constructed with; ``None``/``-1`` spec dims resolve to
        ``batch_size``.
        """
        from ..jit.dy2static.program_translator import ProgramTranslator
        from ..static import program as _prog_mod
        from ..static.passes.memory_plan import build_memory_plan

        specs = (_to_list(input_spec) if input_spec is not None
                 else list(self._inputs or []))
        if not specs:
            raise ValueError(
                "static_memory_plan needs input specs: pass input_spec= "
                "or construct Model(net, inputs=[InputSpec(...)])")
        lspecs = []
        if mode == "train":
            if self._loss is None:
                raise ValueError(
                    "static_memory_plan(mode='train') requires "
                    "prepare(loss=...) first; use mode='eval' for a "
                    "forward-only plan")
            lspecs = (_to_list(label_spec) if label_spec is not None
                      else list(self._labels or []))
            if not lspecs:
                raise ValueError(
                    "static_memory_plan(mode='train') needs label specs: "
                    "pass label_spec= or construct "
                    "Model(net, inputs=..., labels=[InputSpec(...)])")
        elif mode != "eval":
            raise ValueError(f"mode must be 'train' or 'eval', got {mode!r}")

        net, loss_fn, n_in = self.network, self._loss, len(specs)
        if mode == "train":
            def _capture(*args):
                outs = _to_list(net.forward(*args[:n_in]))
                return loss_fn(*(outs + list(args[n_in:])))
        else:
            def _capture(*args):
                return net.forward(*args)

        prog, feeds, fetch = ProgramTranslator.get_instance().get_program(
            _capture, specs + lspecs)
        fetch_names = [v.name for v in fetch]
        if mode == "train":
            # fetch the grads too: with only the loss fetched, liveness
            # would mark every backward op dead and the plan would
            # degenerate to the forward view
            pairs = _prog_mod.append_backward(fetch[0])
            fetch_names = [fetch[0].name] + [g.name for _, g in pairs]

        feed_shapes, feed_dtypes = {}, {}
        for v, spec in zip(feeds, specs + lspecs):
            feed_shapes[v.name] = tuple(
                batch_size if d in (None, -1) else int(d)
                for d in spec.shape)
            feed_dtypes[v.name] = str(getattr(spec, "dtype", "float32"))
        return build_memory_plan(prog, feed_shapes=feed_shapes,
                                 feed_dtypes=feed_dtypes,
                                 fetch_names=fetch_names)

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        from .summary_mod import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)

"""hapi callbacks (reference python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "EarlyStopping",
           "LRSchedulerCallback", "VisualDL", "ProfilerCallback",
           "config_callbacks"]


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)

    @property
    def stop_training(self):
        return any(getattr(c, "stop_training", False)
                   for c in self.callbacks)


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.step = 0
        self._epoch_t0 = time.time()
        self._ips_t0 = self._epoch_t0
        self._ips_samples = 0

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if k == "batch_size":        # loop metadata, not a metric
                continue
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
            elif isinstance(v, (list, tuple, np.ndarray)):
                parts.append(f"{k}: " + ",".join(f"{x:.4f}" for x in
                                                 np.ravel(v)[:4]))
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        # logs['loss'] is a LAZY device scalar in the async fit loop —
        # it must only be coerced (via _fmt) on log_freq boundaries, so
        # the steady-state loop blocks on the device at most once per
        # window (tools/pipeline_gate.py + test_async_pipeline pin this)
        self.step = step
        self._ips_samples += ((logs or {}).get("batch_size")
                              or self.params.get("batch_size") or 0)
        if self.verbose >= 2 and step % self.log_freq == 0:
            total = self.params.get("steps")
            msg = (f"Epoch {self.epoch + 1}/{self.epochs} "
                   f"step {step}/{total} - {self._fmt(logs)}")
            # ips over the window since the last log line (reference
            # hapi ProgBarLogger reports "ips: N samples/sec")
            now = time.time()
            dt = now - self._ips_t0
            if self._ips_samples and dt > 0:
                msg += f" - ips: {self._ips_samples / dt:.2f} samples/s"
            self._ips_t0 = now
            self._ips_samples = 0
            print(msg, flush=True)

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose >= 1:
            dt = time.time() - self._epoch_t0
            print(f"Epoch {epoch + 1}/{self.epochs} done in {dt:.1f}s "
                  f"- {self._fmt(logs)}", flush=True)

    def on_eval_end(self, logs=None):
        if self.verbose >= 1:
            print(f"Eval - {self._fmt(logs)}", flush=True)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stop_training = False
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.best = None
        self.wait = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        cur = float(np.ravel(cur)[0]) if isinstance(
            cur, (list, tuple, np.ndarray)) else float(cur)
        if self._better(cur):
            self.best = cur
            self.wait = 0
            if self.save_best_model and getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir,
                                             "best_model"))
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: stop (best {self.monitor}="
                          f"{self.best:.5f})")


class LRSchedulerCallback(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step != by_epoch
        self.by_step = by_step

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if not self.by_step and s is not None:
            s.step()


class VisualDL(Callback):
    """Scalar logging callback.  VisualDL itself isn't in this image;
    writes a plain jsonl the dashboard (or any reader) can tail.

    Per-step values are buffered as-is and only coerced to float at
    flush points (every ``flush_every`` steps, epoch end, train end) —
    ``logs['loss']`` is a lazy device scalar in the async fit loop, and
    coercing it every step would reintroduce the per-step host sync
    this pipeline removes.  A crash mid-window loses at most
    ``flush_every`` steps of scalars; shrink it (or 1 for the old
    write-per-step behavior) when post-mortem completeness matters more
    than pipeline depth."""

    def __init__(self, log_dir="vdl_log", flush_every=64):
        super().__init__()
        self.log_dir = log_dir
        self.flush_every = max(1, int(flush_every))
        self._f = None
        self._buf = []

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "scalars.jsonl"), "a")

    def _flush(self):
        import json
        if not self._f:
            self._buf.clear()
            return
        for step, rec in self._buf:
            out = {"step": step}
            for k, v in rec:
                out[k] = float(v)   # lazy scalars materialize here
            self._f.write(json.dumps(out) + "\n")
        self._buf.clear()

    def on_train_batch_end(self, step, logs=None):
        if self._f and logs:
            self._buf.append((step, [(k, v) for k, v in logs.items()
                                     if k != "batch_size" and
                                     isinstance(v, numbers.Number)]))
            if len(self._buf) >= self.flush_every:
                self._flush()

    def on_epoch_end(self, epoch, logs=None):
        self._flush()

    def on_train_end(self, logs=None):
        if self._f:
            self._flush()
            self._f.close()


class ProfilerCallback(Callback):
    """Drives a ``paddle.profiler.Profiler`` across ``Model.fit``.

    Pass a ready Profiler, or scheduler/on_trace_ready/etc. kwargs to
    build one.  ``start()`` fires at train begin, ``step(num_samples)``
    after every batch (so step latency and ips are measured around the
    real train step), ``stop()`` + optional ``summary()`` at train end.
    """

    def __init__(self, profiler=None, summary=True, **profiler_kwargs):
        super().__init__()
        if profiler is None:
            from .. import profiler as _prof_mod
            profiler = _prof_mod.Profiler(**profiler_kwargs)
        self.profiler = profiler
        self.print_summary = summary

    def on_train_begin(self, logs=None):
        self.profiler.start()

    def on_train_batch_end(self, step, logs=None):
        self.profiler.step(num_samples=((logs or {}).get("batch_size")
                                        or self.params.get("batch_size")))

    def on_train_end(self, logs=None):
        self.profiler.stop()
        if self.print_summary:
            self.profiler.summary()


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, LRSchedulerCallback) for c in cbks) and \
            mode == "train":
        cbks = cbks + [LRSchedulerCallback()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"batch_size": batch_size, "epochs": epochs,
                    "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst

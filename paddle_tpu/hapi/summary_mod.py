"""Model summary + flops (reference python/paddle/hapi/model_summary.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["summary", "flops"]


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print per-layer output shapes + param counts; returns totals."""
    from .. import ops

    rows = []
    hooks = []

    def make_hook(name):
        def hook(layer, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else "?"
            n_params = sum(int(np.prod(p.shape)) for p in
                           layer.parameters(include_sublayers=False))
            rows.append((name, type(layer).__name__, shape, n_params))
        return hook

    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(name)))

    if input is not None:
        x = input if isinstance(input, (list, tuple)) else [input]
    else:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = input_size if isinstance(input_size, list) and \
            isinstance(input_size[0], (list, tuple)) else [input_size]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes or "float32"] * len(sizes)
        x = [ops.creation.zeros(list(s), dt) for s, dt in zip(sizes, dts)]
    was_training = net.training
    net.eval()
    try:
        net(*x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total_params = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)
    width = 76
    print("-" * width)
    print(f"{'Layer (type)':<34}{'Output Shape':<26}{'Param #':<12}")
    print("=" * width)
    for name, tname, shape, n in rows:
        print(f"{name + ' (' + tname + ')':<34}{str(shape):<26}{n:<12,}")
    print("=" * width)
    print(f"Total params: {total_params:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total_params - trainable:,}")
    print("-" * width)
    return {"total_params": total_params, "trainable_params": trainable}


def flops(net: Layer, input_size, custom_ops=None, print_detail=False):
    """Rough flops accounting for the common layer types."""
    from ..nn.layer import common, conv as conv_mod, norm as norm_mod
    from .. import ops as ops_mod

    total = [0]
    hooks = []

    def conv_hook(layer, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
        k = int(np.prod(layer._kernel_size))
        cin = layer._in_channels // layer._groups
        total[0] += 2 * k * cin * int(np.prod(out.shape))

    def linear_hook(layer, inputs, outputs):
        out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
        total[0] += 2 * layer._in_features * int(np.prod(out.shape))

    for _, sub in net.named_sublayers():
        if isinstance(sub, conv_mod._ConvNd):
            hooks.append(sub.register_forward_post_hook(conv_hook))
        elif isinstance(sub, common.Linear):
            hooks.append(sub.register_forward_post_hook(linear_hook))

    x = ops_mod.creation.zeros(list(input_size))
    was_training = net.training
    net.eval()
    try:
        net(x)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()
    if print_detail:
        print(f"Total FLOPs: {total[0]:,}")
    return total[0]

from .model import Model  # noqa: F401
from .summary_mod import summary, flops  # noqa: F401
from . import callbacks  # noqa: F401

"""Device management namespace (``paddle.device`` parity).

Reference parity: ``python/paddle/device/__init__.py`` — set_device
(:182), get_device (:209), is_compiled_with_* probes (:41,:56), plus the
``paddle.device.cuda`` submodule (mirrored here as ``tpu``).

TPU-first: devices are PJRT devices enumerated by JAX; ``set_device``
pins the default placement used by eager tensor creation.
"""
from __future__ import annotations

import jax

from ..core.place import (  # noqa: F401
    CPUPlace, TPUPlace, CUDAPinnedPlace, set_device, get_device,
    device_count, is_compiled_with_tpu,
)

__all__ = [
    "set_device", "get_device", "device_count", "get_all_device_type",
    "get_all_custom_device_type", "get_available_device",
    "get_available_custom_device", "is_compiled_with_tpu",
    "is_compiled_with_cuda", "is_compiled_with_npu", "is_compiled_with_xpu",
    "get_cudnn_version", "XPUPlace", "CPUPlace", "TPUPlace",
    "CUDAPinnedPlace", "synchronize",
]


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def get_cudnn_version():
    return None


def XPUPlace(dev_id):
    raise RuntimeError(
        "paddle_tpu is not compiled with XPU; use set_device('tpu')")


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()} | {"cpu"})


def get_all_custom_device_type():
    return []


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def synchronize(device=None):
    """Block until all queued device work completes
    (reference ``device/cuda/__init__.py`` synchronize; PJRT analog)."""
    for d in jax.devices():
        try:
            jax.block_until_ready(jax.device_put(0, d))
        except Exception:
            pass

def save(obj, path, **kw):
    raise NotImplementedError("stub")
def load(path, **kw):
    raise NotImplementedError("stub")

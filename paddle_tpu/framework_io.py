"""paddle.save / paddle.load.

Reference parity: ``python/paddle/framework/io.py:553,769`` — pickled
nested structures of numpy-ified tensors, >4GB pickle protocol, separate
optimizer-state dicts.  Sharded/async checkpointing for meshes lives in
``distributed.checkpoint`` (orbax-style); this is the single-host format.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core.tensor import Parameter, Tensor

__all__ = ["save", "load"]

_PROTOCOL = 4


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return {"__tensor__": True, "data": np.asarray(obj._data),
                "stop_gradient": obj.stop_gradient, "name": obj.name,
                "is_parameter": isinstance(obj, Parameter)}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj):
    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            import jax.numpy as jnp
            cls = Parameter if obj.get("is_parameter") else Tensor
            t = cls(jnp.asarray(obj["data"]))
            t.stop_gradient = obj.get("stop_gradient", True)
            if obj.get("name"):
                t.name = obj["name"]
            return t
        return {k: _from_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=_PROTOCOL, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    if not os.path.exists(path):
        raise ValueError(f"checkpoint path '{path}' does not exist")
    with open(path, "rb") as f:
        return _from_saveable(pickle.load(f))

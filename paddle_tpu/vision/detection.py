"""Detection op family.

Reference parity: ``paddle/fluid/operators/detection/`` — prior_box
(prior_box_op.h:53), density_prior_box (density_prior_box_op.h:30),
anchor_generator (anchor_generator_op.h:31), box_coder
(box_coder_op.h:38), iou_similarity (iou_similarity_op.h:55), box_clip
(box_clip_op.h:30), bipartite_match (bipartite_match_op.cc:72),
target_assign (target_assign_op.h:48), multiclass_nms
(multiclass_nms_op.cc:82), matrix_nms (matrix_nms_op.cc:144),
locality_aware_nms (locality_aware_nms_op.cc:186), generate_proposals
(generate_proposals_op.cc:57), distribute_fpn_proposals
(distribute_fpn_proposals_op.h:47), collect_fpn_proposals
(collect_fpn_proposals_op.h:55), rpn_target_assign
(rpn_target_assign_op.cc:214), retinanet_target_assign
(rpn_target_assign_op.cc:578), retinanet_detection_output
(retinanet_detection_output_op.cc:154), generate_proposal_labels
(generate_proposal_labels_op.cc:64), generate_mask_labels
(generate_mask_labels_op.cc:82), mine_hard_examples
(mine_hard_examples_op.cc:54), detection_map (detection_map_op.h:52),
mean_iou (mean_iou_op.h:28), box_decoder_and_assign
(box_decoder_and_assign_op.h:27).

TPU-first split: pure box geometry (priors, coding, IoU, clipping,
mean-IoU) is jnp and jit/grad-friendly; the data-dependent stages (NMS,
proposal generation, target sampling) run on host in numpy — the
reference runs these same stages on CPU kernels with dynamic output
LoD, which has no fixed-shape XLA analog, so host execution IS the
reference architecture here, feeding fixed-shape device stages around
it.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "iou_similarity", "box_coder", "box_clip", "prior_box",
    "density_prior_box", "anchor_generator", "bipartite_match",
    "target_assign", "multiclass_nms", "matrix_nms",
    "locality_aware_nms", "generate_proposals",
    "distribute_fpn_proposals", "collect_fpn_proposals",
    "rpn_target_assign", "retinanet_target_assign",
    "retinanet_detection_output", "generate_proposal_labels",
    "generate_mask_labels", "mine_hard_examples", "detection_map",
    "mean_iou", "box_decoder_and_assign", "nms",
]


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(x._data)
    return np.asarray(x)


# ---------------------------------------------------------------------------
# jittable geometry
# ---------------------------------------------------------------------------

def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU of [N,4] and [M,4] boxes -> [N,M]
    (iou_similarity_op.h:55; +1 extent when boxes are pixel coords)."""
    off = 0.0 if box_normalized else 1.0

    def impl(bx, by):
        ax = (bx[:, 2] - bx[:, 0] + off) * (bx[:, 3] - bx[:, 1] + off)
        ay = (by[:, 2] - by[:, 0] + off) * (by[:, 3] - by[:, 1] + off)
        x1 = jnp.maximum(bx[:, None, 0], by[None, :, 0])
        y1 = jnp.maximum(bx[:, None, 1], by[None, :, 1])
        x2 = jnp.minimum(bx[:, None, 2], by[None, :, 2])
        y2 = jnp.minimum(bx[:, None, 3], by[None, :, 3])
        iw = jnp.clip(x2 - x1 + off, 0)
        ih = jnp.clip(y2 - y1 + off, 0)
        inter = iw * ih
        union = ax[:, None] + ay[None, :] - inter
        return jnp.where(union > 0, inter / union, 0.0)

    return dispatch("iou_similarity", impl, (to_tensor(x), to_tensor(y)), {})


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Encode/decode boxes against priors (box_coder_op.h:38).

    encode: target [N,4] x prior [M,4] -> [N,M,4]
    decode: target [N,M,4] (or [N,1,4]/[1,N,4] per axis) -> [N,M,4]
    prior_box_var: None | [M,4] Tensor | list of 4 floats.
    """
    norm = bool(box_normalized)
    var_is_tensor = isinstance(prior_box_var, (Tensor, np.ndarray)) or (
        hasattr(prior_box_var, "shape") and not isinstance(prior_box_var,
                                                           (list, tuple)))
    var_list = (tuple(float(v) for v in prior_box_var)
                if isinstance(prior_box_var, (list, tuple)) else None)

    off = 0.0 if norm else 1.0

    def _prior_cwh(pb):
        pw = pb[..., 2] - pb[..., 0] + off
        ph = pb[..., 3] - pb[..., 1] + off
        pcx = pb[..., 0] + pw / 2
        pcy = pb[..., 1] + ph / 2
        return pcx, pcy, pw, ph

    if code_type == "encode_center_size":
        def impl(pb, tb, *rest):
            pcx, pcy, pw, ph = _prior_cwh(pb)          # [M]
            tcx = (tb[:, 2] + tb[:, 0]) / 2            # [N]
            tcy = (tb[:, 3] + tb[:, 1]) / 2
            tw = tb[:, 2] - tb[:, 0] + off
            th = tb[:, 3] - tb[:, 1] + off
            out = jnp.stack([
                (tcx[:, None] - pcx[None, :]) / pw[None, :],
                (tcy[:, None] - pcy[None, :]) / ph[None, :],
                jnp.log(jnp.abs(tw[:, None] / pw[None, :])),
                jnp.log(jnp.abs(th[:, None] / ph[None, :])),
            ], axis=-1)                                # [N,M,4]
            if rest:
                out = out / rest[0][None, :, :]
            elif var_list is not None:
                out = out / jnp.asarray(var_list)
            return out

        args = [to_tensor(prior_box), to_tensor(target_box)]
        if var_is_tensor:
            args.append(to_tensor(prior_box_var))
        return dispatch("box_coder", impl, tuple(args), {})

    if code_type != "decode_center_size":
        raise ValueError(f"unknown code_type {code_type!r}")

    def impl(pb, tb, *rest):
        # pb: [M,4]; tb: [N,M,4] (axis=0 -> prior per column,
        # axis=1 -> prior per row)
        pcx, pcy, pw, ph = _prior_cwh(pb)
        if axis == 0:
            pcx, pcy, pw, ph = (v[None, :] for v in (pcx, pcy, pw, ph))
            vshape = (1, -1, 4)
        else:
            pcx, pcy, pw, ph = (v[:, None] for v in (pcx, pcy, pw, ph))
            vshape = (-1, 1, 4)
        if rest:
            var = rest[0].reshape(vshape)
            vx, vy, vw, vh = (var[..., k] for k in range(4))
        elif var_list is not None:
            vx, vy, vw, vh = var_list
        else:
            vx = vy = vw = vh = 1.0
        tcx = vx * tb[..., 0] * pw + pcx
        tcy = vy * tb[..., 1] * ph + pcy
        tw = jnp.exp(vw * tb[..., 2]) * pw
        th = jnp.exp(vh * tb[..., 3]) * ph
        return jnp.stack([tcx - tw / 2, tcy - th / 2,
                          tcx + tw / 2 - off, tcy + th / 2 - off], axis=-1)

    args = [to_tensor(prior_box), to_tensor(target_box)]
    if var_is_tensor:
        args.append(to_tensor(prior_box_var))
    return dispatch("box_coder", impl, tuple(args), {})


def box_clip(input, im_info, name=None):
    """Clip [..., 4] boxes to image extent (box_clip_op.h:30); im_info
    rows are (height, width, scale)."""
    def impl(boxes, info):
        h = info[..., 0] / info[..., 2] - 1
        w = info[..., 1] / info[..., 2] - 1
        shape = boxes.shape
        b = boxes.reshape(info.shape[0], -1, 4) if info.ndim == 2 else boxes
        hh = h.reshape(-1, 1) if info.ndim == 2 else h
        ww = w.reshape(-1, 1) if info.ndim == 2 else w
        out = jnp.stack([
            jnp.clip(b[..., 0], 0, ww), jnp.clip(b[..., 1], 0, hh),
            jnp.clip(b[..., 2], 0, ww), jnp.clip(b[..., 3], 0, hh),
        ], axis=-1)
        return out.reshape(shape)

    return dispatch("box_clip", impl,
                    (to_tensor(input), to_tensor(im_info)), {})


def _expand_aspect_ratios(aspect_ratios, flip):
    out = [1.0]
    for ar in aspect_ratios:
        if any(abs(ar - o) < 1e-6 for o in out):
            continue
        out.append(float(ar))
        if flip:
            out.append(1.0 / float(ar))
    return out


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes over a feature map (prior_box_op.h:53).
    Returns (boxes [H,W,P,4], variances [H,W,P,4]), normalized coords."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    ars = _expand_aspect_ratios([float(a) for a in aspect_ratios], flip)
    min_sizes = [float(m) for m in min_sizes]
    max_sizes = [float(m) for m in (max_sizes or [])]
    if max_sizes:
        assert len(max_sizes) == len(min_sizes)

    cx = (np.arange(fw) + offset) * step_w            # [W]
    cy = (np.arange(fh) + offset) * step_h            # [H]
    cx, cy = np.meshgrid(cx, cy)                      # [H,W]
    whs: List[Tuple[float, float]] = []
    for s, mn in enumerate(min_sizes):
        if min_max_aspect_ratios_order:
            whs.append((mn / 2.0, mn / 2.0))
            if max_sizes:
                m = math.sqrt(mn * max_sizes[s]) / 2.0
                whs.append((m, m))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((mn * math.sqrt(ar) / 2, mn / math.sqrt(ar) / 2))
        else:
            for ar in ars:
                whs.append((mn * math.sqrt(ar) / 2, mn / math.sqrt(ar) / 2))
            if max_sizes:
                m = math.sqrt(mn * max_sizes[s]) / 2.0
                whs.append((m, m))
    wh = np.asarray(whs, np.float32)                  # [P,2]
    boxes = np.stack([
        (cx[..., None] - wh[None, None, :, 0]) / iw,
        (cy[..., None] - wh[None, None, :, 1]) / ih,
        (cx[..., None] + wh[None, None, :, 0]) / iw,
        (cy[..., None] + wh[None, None, :, 1]) / ih,
    ], axis=-1).astype(np.float32)                    # [H,W,P,4]
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Densified priors (density_prior_box_op.h:30): each fixed_size is
    laid out on a density x density sub-grid inside the step cell."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_w = steps[0] or iw / fw
    step_h = steps[1] or ih / fh
    densities = [int(d) for d in densities]
    fixed_sizes = [float(s) for s in fixed_sizes]
    fixed_ratios = [float(r) for r in fixed_ratios]

    # per-cell prior pattern is identical everywhere: compute the
    # [num, 4] center offsets once, broadcast-add the center grid
    offs = []
    for size, dens in zip(fixed_sizes, densities):
        for ar in fixed_ratios:
            bw = size * math.sqrt(ar)
            bh = size / math.sqrt(ar)
            shift = size / dens
            dj, di = np.meshgrid(np.arange(dens), np.arange(dens))
            c_x = (-size / 2 + shift / 2 + dj * shift).reshape(-1)
            c_y = (-size / 2 + shift / 2 + di * shift).reshape(-1)
            offs.append(np.stack([c_x - bw / 2, c_y - bh / 2,
                                  c_x + bw / 2, c_y + bh / 2], axis=1))
    offs = np.concatenate(offs, axis=0)               # [num, 4]
    num = offs.shape[0]
    cx = (np.arange(fw) + offset) * step_w
    cy = (np.arange(fh) + offset) * step_h
    cx, cy = np.meshgrid(cx, cy)                      # [H, W]
    centers = np.stack([cx, cy, cx, cy], axis=-1)     # [H, W, 4]
    boxes = (centers[:, :, None, :] + offs[None, None]) / \
        np.asarray([iw, ih, iw, ih], np.float64)
    boxes = boxes.astype(np.float32)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          boxes.shape).copy()
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(var))


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """RPN anchors in pixel coords (anchor_generator_op.h:31).
    Returns (anchors [H,W,A,4], variances [H,W,A,4])."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    sw, sh = float(stride[0]), float(stride[1])
    whs = []
    for ar in aspect_ratios:
        for sz in anchor_sizes:
            # reference convention (anchor_generator_op.h:80-95):
            # base anchor from rounded sqrt of stride area / ar, scaled
            # by size/stride; half-extent is 0.5*(w-1) pixel-inclusive
            area = sw * sh
            area_ratio = area / float(ar)
            base_w = round(math.sqrt(area_ratio))
            base_h = round(base_w * float(ar))
            aw = (float(sz) / sw) * base_w
            ah = (float(sz) / sh) * base_h
            whs.append((0.5 * (aw - 1), 0.5 * (ah - 1)))
    wh = np.asarray(whs, np.float32)                  # [A,2]
    cx = np.arange(fw) * sw + offset * (sw - 1)
    cy = np.arange(fh) * sh + offset * (sh - 1)
    cx, cy = np.meshgrid(cx, cy)
    anchors = np.stack([
        cx[..., None] - wh[None, None, :, 0],
        cy[..., None] - wh[None, None, :, 1],
        cx[..., None] + wh[None, None, :, 0],
        cy[..., None] + wh[None, None, :, 1],
    ], axis=-1).astype(np.float32)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          anchors.shape).copy()
    return Tensor(jnp.asarray(anchors)), Tensor(jnp.asarray(var))


def mean_iou(input, label, num_classes, name=None):
    """Segmentation mean-IoU (mean_iou_op.h:28).  Returns
    (mean_iou scalar, out_wrong [C], out_correct [C])."""
    def impl(pred, lab):
        pred = pred.reshape(-1).astype(jnp.int32)
        lab = lab.reshape(-1).astype(jnp.int32)
        correct = jnp.zeros((num_classes,), jnp.int32).at[
            jnp.where(pred == lab, pred, num_classes)].add(
                1, mode="drop")
        pred_cnt = jnp.zeros((num_classes,), jnp.int32).at[pred].add(1)
        lab_cnt = jnp.zeros((num_classes,), jnp.int32).at[lab].add(1)
        union = pred_cnt + lab_cnt - correct
        valid = union > 0
        iou = jnp.where(valid, correct / jnp.maximum(union, 1), 0.0)
        miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
        wrong = pred_cnt - correct
        return miou.astype(jnp.float32), wrong, correct

    out = dispatch("mean_iou", impl, (to_tensor(input), to_tensor(label)),
                   {})
    return out


# ---------------------------------------------------------------------------
# host-side (data-dependent) kernels
# ---------------------------------------------------------------------------

def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    """Greedy maximum bipartite matching (bipartite_match_op.cc:72).
    dist_matrix: [N,M] or [B,N,M].  Returns (match_indices [B,M] int32,
    match_dist [B,M] float32); -1 where a column is unmatched."""
    d = _np(dist_matrix).astype(np.float64)
    batched = d.ndim == 3
    mats = d if batched else d[None]
    B, N, M = mats.shape
    all_idx = np.full((B, M), -1, np.int32)
    all_dist = np.zeros((B, M), np.float32)
    for b in range(B):
        mat = mats[b].copy()
        row_used = np.zeros(N, bool)
        col_used = np.zeros(M, bool)
        work = mat.copy()
        while True:
            flat = np.argmax(work)
            i, j = divmod(int(flat), M)
            if work[i, j] <= 0:
                break
            all_idx[b, j] = i
            all_dist[b, j] = mat[i, j]
            row_used[i] = True
            col_used[j] = True
            work[i, :] = -1
            work[:, j] = -1
            if row_used.all() or col_used.all():
                break
        if match_type == "per_prediction":
            for j in range(M):
                if all_idx[b, j] >= 0:
                    continue
                i = int(np.argmax(mat[:, j]))
                if mat[i, j] >= dist_threshold:
                    all_idx[b, j] = i
                    all_dist[b, j] = mat[i, j]
    if not batched:
        pass  # keep leading batch dim of 1, matching the LoD output shape
    return Tensor(jnp.asarray(all_idx)), Tensor(jnp.asarray(all_dist))


def target_assign(input, match_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    """Gather per-prior targets by match indices (target_assign_op.h:48).
    input: [N_gt, K] per batch stacked as [B, N_gt, K] (or [N_gt, K] for
    B=1); match_indices: [B, M].  Returns (out [B, M, K], out_weight
    [B, M, 1])."""
    x = _np(input)
    mi = _np(match_indices).astype(np.int64)
    if x.ndim == 2:
        x = np.broadcast_to(x[None], (mi.shape[0],) + x.shape)
    B, M = mi.shape
    K = x.shape[-1]
    out = np.full((B, M, K), mismatch_value, x.dtype)
    wt = np.zeros((B, M, 1), np.float32)
    for b in range(B):
        pos = mi[b] >= 0
        out[b, pos] = x[b, mi[b, pos]]
        wt[b, pos] = 1.0
    if negative_indices is not None:
        neg = _np(negative_indices).astype(np.int64)
        if neg.ndim == 1:
            neg = neg[None]
        for b in range(B):
            idx = neg[b][neg[b] >= 0]
            out[b, idx] = mismatch_value
            wt[b, idx] = 1.0
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(wt))


def _iou_np(a, b, off=1.0):
    x1 = np.maximum(a[:, None, 0], b[None, :, 0])
    y1 = np.maximum(a[:, None, 1], b[None, :, 1])
    x2 = np.minimum(a[:, None, 2], b[None, :, 2])
    y2 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.clip(x2 - x1 + off, 0, None) * np.clip(y2 - y1 + off, 0, None)
    aa = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    ab = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    union = aa[:, None] + ab[None, :] - inter
    return np.where(union > 0, inter / union, 0.0)


def _nms_kernel(boxes, scores, nms_threshold, eta=1.0, top_k=-1,
                normalized=True):
    """Greedy hard-NMS indices, adaptive threshold via eta
    (multiclass_nms_op.cc NMSFast)."""
    order = np.argsort(-scores, kind="stable")
    if top_k >= 0:
        order = order[:top_k]
    off = 0.0 if normalized else 1.0
    keep: list = []
    thr = float(nms_threshold)
    boxes = np.asarray(boxes, np.float64)
    for i in order:
        if keep:
            # one broadcasted IoU row per candidate vs the kept set
            iou = _iou_np(boxes[i][None], boxes[np.asarray(keep)], off)[0]
            if (iou > thr).any():
                continue
        keep.append(int(i))
        if eta < 1.0 and thr > 0.5:
            thr *= eta
    return keep


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   rois_num=None, name=None):
    """Batched multiclass hard NMS (multiclass_nms_op.cc:82; the v2/v3
    ops add Index / NmsRoisNum outputs — both returned here).

    bboxes: [B, M, 4]; scores: [B, C, M].  Returns (out [K,6],
    index [K,1], nms_rois_num [B]); out rows are
    (label, score, x1, y1, x2, y2).
    """
    b = _np(bboxes)
    s = _np(scores)
    assert b.ndim == 3 and s.ndim == 3, "expect [B,M,4] boxes, [B,C,M] scores"
    B, M, _ = b.shape
    C = s.shape[1]
    all_rows, all_idx, rois_n = [], [], []
    for bi in range(B):
        cand = []   # (score, class, box_idx)
        for c in range(C):
            if c == background_label:
                continue
            sc = s[bi, c]
            mask = sc > score_threshold
            idxs = np.where(mask)[0]
            if idxs.size == 0:
                continue
            keep = _nms_kernel(b[bi][idxs], sc[idxs], nms_threshold,
                               nms_eta, nms_top_k, normalized)
            for k in keep:
                cand.append((float(sc[idxs[k]]), c, int(idxs[k])))
        cand.sort(key=lambda t: -t[0])
        if keep_top_k >= 0:
            cand = cand[:keep_top_k]
        rois_n.append(len(cand))
        for score, c, bx in cand:
            all_rows.append([c, score] + list(b[bi, bx]))
            all_idx.append(bi * M + bx)
    out = np.asarray(all_rows, np.float32).reshape(-1, 6)
    idx = np.asarray(all_idx, np.int32).reshape(-1, 1)
    nums = np.asarray(rois_n, np.int32)
    res = (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(idx)),
           Tensor(jnp.asarray(nums)))
    return res if return_index else (res[0], res[2])


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Soft suppression via decayed scores (matrix_nms_op.cc:144).
    Returns (out [K,6], rois_num [B][, index [K,1]])."""
    b = _np(bboxes)
    s = _np(scores)
    B, M, _ = b.shape
    C = s.shape[1]
    off = 0.0 if normalized else 1.0
    all_rows, all_idx, rois_n = [], [], []
    for bi in range(B):
        rows = []
        for c in range(C):
            if c == background_label:
                continue
            sc = s[bi, c]
            sel = np.where(sc > score_threshold)[0]
            if sel.size == 0:
                continue
            order = sel[np.argsort(-sc[sel], kind="stable")][:nms_top_k]
            bx = b[bi][order]
            ss = sc[order].astype(np.float64)
            n = len(order)
            iou = np.triu(_iou_np(bx, bx, off), 1)       # iou[j, i]: j earlier (higher score)
            # decay for i: min_{j<i} f(iou_ji) / f(compensate_j), where
            # compensate_j = max_{k<j} iou_kj (how suppressed j itself is)
            compensate = iou.max(axis=0)     # column max = per-box j
            if use_gaussian:
                decay = np.exp(-gaussian_sigma *
                               (iou ** 2 - compensate[:, None] ** 2))
            else:
                decay = (1.0 - iou) / (1.0 - compensate[:, None] + 1e-10)
            decay = np.where(np.triu(np.ones((n, n), bool), 1), decay, 1.0)
            decay_i = decay.min(axis=0)
            new_scores = ss * decay_i
            for k in range(n):
                if new_scores[k] > post_threshold:
                    rows.append((float(new_scores[k]), c, int(order[k])))
        rows.sort(key=lambda t: -t[0])
        if keep_top_k >= 0:
            rows = rows[:keep_top_k]
        rois_n.append(len(rows))
        for score, c, k in rows:
            all_rows.append([c, score] + list(b[bi, k]))
            all_idx.append(bi * M + k)
    out = np.asarray(all_rows, np.float32).reshape(-1, 6)
    idx = np.asarray(all_idx, np.int32).reshape(-1, 1)
    nums = np.asarray(rois_n, np.int32)
    res = [Tensor(jnp.asarray(out))]
    if return_rois_num:
        res.append(Tensor(jnp.asarray(nums)))
    if return_index:
        res.append(Tensor(jnp.asarray(idx)))
    return tuple(res) if len(res) > 1 else res[0]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """paddle.vision.ops.nms-style single-image NMS.  Returns kept
    indices sorted by score (or box order when scores is None)."""
    b = _np(boxes)
    s = (_np(scores) if scores is not None
         else np.arange(b.shape[0], 0, -1, dtype=np.float32))
    if category_idxs is None:
        keep = _nms_kernel(b, s, iou_threshold)
    else:
        cats = _np(category_idxs)
        keep = []
        for c in (categories if categories is not None
                  else np.unique(cats)):
            idxs = np.where(cats == c)[0]
            if idxs.size:
                kept = _nms_kernel(b[idxs], s[idxs], iou_threshold)
                keep.extend(int(idxs[k]) for k in kept)
        keep.sort(key=lambda i: -s[i])
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(np.asarray(keep, np.int64)))


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """EAST-style NMS (locality_aware_nms_op.cc:186): consecutive
    overlapping boxes are score-weighted-merged first, then hard NMS.
    Single class typical; inputs as multiclass_nms."""
    b = _np(bboxes).copy()
    s = _np(scores).copy()
    B, M, _ = b.shape
    C = s.shape[1]
    off = 0.0 if normalized else 1.0
    all_rows, rois_n = [], []

    def iou_one(p, q):
        return float(_iou_np(np.asarray(p)[None], np.asarray(q)[None],
                             off)[0, 0])

    for bi in range(B):
        cand = []
        for c in range(C):
            if c == background_label:
                continue
            sc = s[bi, c].copy()
            bx = b[bi].copy()
            # locality-aware merge pass over input order (reference
            # GetMaxScoreIndexWithLocalityAware: weighted-merge into the
            # running box, score accumulates by SUM, and the
            # score_threshold applies AFTER merging)
            merged_b, merged_s = [], []
            for m in range(M):
                if merged_b and iou_one(merged_b[-1], bx[m]) > nms_threshold:
                    w1, w2 = merged_s[-1], float(sc[m])
                    merged_b[-1] = (merged_b[-1] * w1 + bx[m] * w2) / \
                        (w1 + w2)
                    merged_s[-1] = w1 + w2
                else:
                    merged_b.append(bx[m].astype(np.float64))
                    merged_s.append(float(sc[m]))
            if not merged_b:
                continue
            mb = np.asarray(merged_b)
            ms = np.asarray(merged_s)
            keep_mask = ms > score_threshold
            mb, ms = mb[keep_mask], ms[keep_mask]
            if mb.shape[0] == 0:
                continue
            keep = _nms_kernel(mb, ms, nms_threshold, nms_eta, nms_top_k,
                               normalized)
            for k in keep:
                cand.append((float(ms[k]), c, mb[k]))
        cand.sort(key=lambda t: -t[0])
        if keep_top_k >= 0:
            cand = cand[:keep_top_k]
        rois_n.append(len(cand))
        for score, c, box in cand:
            all_rows.append([c, score] + list(box))
    out = np.asarray(all_rows, np.float32).reshape(-1, 6)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(
        np.asarray(rois_n, np.int32)))


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=True, return_rois_num=True, name=None):
    """RPN proposal generation (generate_proposals_op.cc:57; the _v2 op
    swaps im_info for im_shape ≡ pixel_offset=False path).

    scores [N,A,H,W], bbox_deltas [N,4A,H,W], im_info [N,3] (or im_shape
    [N,2]), anchors [H,W,A,4]|[HWA,4], variances same.
    Returns (rpn_rois [K,4], rpn_roi_probs [K,1], rois_num [N]).
    """
    sc = _np(scores)
    bd = _np(bbox_deltas)
    info = _np(im_info)
    an = _np(anchors).reshape(-1, 4)
    va = _np(variances).reshape(-1, 4)
    N, A, H, W = sc.shape
    rois, probs, nums = [], [], []
    off = 1.0 if pixel_offset else 0.0
    for n in range(N):
        s1 = sc[n].transpose(1, 2, 0).reshape(-1)          # HWA
        d1 = bd[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s1, kind="stable")[:pre_nms_top_n]
        s2, d2, an2, va2 = s1[order], d1[order], an[order], va[order]
        # decode (variance-scaled center-size, like the reference's
        # box_coder decode with per-anchor variance)
        pw = an2[:, 2] - an2[:, 0] + off
        ph = an2[:, 3] - an2[:, 1] + off
        pcx = an2[:, 0] + pw / 2
        pcy = an2[:, 1] + ph / 2
        cx = va2[:, 0] * d2[:, 0] * pw + pcx
        cy = va2[:, 1] * d2[:, 1] * ph + pcy
        w = np.exp(np.minimum(va2[:, 2] * d2[:, 2], 10.0)) * pw
        h = np.exp(np.minimum(va2[:, 3] * d2[:, 3], 10.0)) * ph
        boxes = np.stack([cx - w / 2, cy - h / 2,
                          cx + w / 2 - off, cy + h / 2 - off], axis=1)
        # clip to image
        if info.shape[1] == 3:
            ih, iw = info[n, 0], info[n, 1]
            scale = info[n, 2]
        else:
            ih, iw = info[n, 0], info[n, 1]
            scale = 1.0
        boxes[:, 0] = np.clip(boxes[:, 0], 0, iw - off)
        boxes[:, 1] = np.clip(boxes[:, 1], 0, ih - off)
        boxes[:, 2] = np.clip(boxes[:, 2], 0, iw - off)
        boxes[:, 3] = np.clip(boxes[:, 3], 0, ih - off)
        # filter tiny boxes (min_size scaled to input image)
        ms = max(min_size, 1.0) * scale if info.shape[1] == 3 else \
            max(min_size, 1.0)
        ww = boxes[:, 2] - boxes[:, 0] + off
        hh = boxes[:, 3] - boxes[:, 1] + off
        keep_mask = (ww >= ms) & (hh >= ms)
        boxes, s3 = boxes[keep_mask], s2[keep_mask]
        if boxes.shape[0] == 0:
            nums.append(0)
            continue
        keep = _nms_kernel(boxes, s3, nms_thresh, eta,
                           normalized=not pixel_offset)
        keep = keep[:post_nms_top_n]
        rois.append(boxes[keep])
        probs.append(s3[keep])
        nums.append(len(keep))
    rois = (np.concatenate(rois) if rois
            else np.zeros((0, 4), np.float32))
    probs = (np.concatenate(probs) if probs
             else np.zeros((0,), np.float32))
    out = (Tensor(jnp.asarray(rois.astype(np.float32))),
           Tensor(jnp.asarray(probs.astype(np.float32).reshape(-1, 1))))
    if return_rois_num:
        out = out + (Tensor(jnp.asarray(np.asarray(nums, np.int32))),)
    return out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=True, rois_num=None,
                             name=None):
    """Scatter RoIs onto FPN levels by scale
    (distribute_fpn_proposals_op.h:47).  Returns (multi_rois list,
    restore_ind [K,1], rois_num_per_level list)."""
    rois = _np(fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.clip(w * h, 0, None))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-6)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    # per-image grouping: with rois_num = [B] counts, each level's
    # rois_num_per_level is the per-image [B] count at that level
    # (reference groups by LoD/RoisNum, distribute_fpn_proposals_op.h:47)
    if rois_num is not None:
        per_img = _np(rois_num).reshape(-1).astype(np.int64)
        img_of = np.repeat(np.arange(per_img.size), per_img)
        if img_of.size != rois.shape[0]:
            raise ValueError(
                f"rois_num sums to {int(per_img.sum())} but fpn_rois has "
                f"{rois.shape[0]} rows")
    else:
        per_img = None
        img_of = None
    multi, nums, order = [], [], []
    for L in range(min_level, max_level + 1):
        idx = np.where(lvl == L)[0]
        multi.append(Tensor(jnp.asarray(rois[idx])))
        if per_img is not None:
            counts = np.bincount(img_of[idx], minlength=per_img.size)
            nums.append(Tensor(jnp.asarray(counts.astype(np.int32))))
        else:
            nums.append(Tensor(jnp.asarray(
                np.asarray([idx.size], np.int32))))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(order.size)
    return multi, Tensor(jnp.asarray(restore.reshape(-1, 1))), nums


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Gather per-level RoIs back, keep top-N by score PER IMAGE
    (collect_fpn_proposals_op.h:55 — the reference groups by LoD batch
    id).  ``rois_num_per_level``: per-level [B] counts; without it a
    single-image batch is assumed.  Returns (fpn_rois, rois_num [B])."""
    rois_l = [_np(r).reshape(-1, 4) for r in multi_rois]
    scores_l = [_np(s).reshape(-1) for s in multi_scores]
    if rois_num_per_level is None:
        nums_l = [np.asarray([r.shape[0]], np.int64) for r in rois_l]
    else:
        nums_l = [_np(n).reshape(-1).astype(np.int64)
                  for n in rois_num_per_level]
    B = nums_l[0].shape[0]
    out_rois, out_nums = [], []
    for b in range(B):
        rs, ss = [], []
        for lvl in range(len(rois_l)):
            start = int(nums_l[lvl][:b].sum())
            cnt = int(nums_l[lvl][b])
            rs.append(rois_l[lvl][start:start + cnt])
            ss.append(scores_l[lvl][start:start + cnt])
        rs = np.concatenate(rs) if rs else np.zeros((0, 4), np.float32)
        ss = np.concatenate(ss) if ss else np.zeros((0,), np.float32)
        order = np.argsort(-ss, kind="stable")[:post_nms_top_n]
        order = np.sort(order)      # reference restores original order
        out_rois.append(rs[order])
        out_nums.append(order.size)
    rois = (np.concatenate(out_rois) if out_rois
            else np.zeros((0, 4), np.float32))
    return (Tensor(jnp.asarray(rois.astype(np.float32))),
            Tensor(jnp.asarray(np.asarray(out_nums, np.int32))))


def _box_encode_np(anchors, gt, off=1.0):
    pw = anchors[:, 2] - anchors[:, 0] + off
    ph = anchors[:, 3] - anchors[:, 1] + off
    pcx = anchors[:, 0] + pw / 2
    pcy = anchors[:, 1] + ph / 2
    gw = gt[:, 2] - gt[:, 0] + off
    gh = gt[:, 3] - gt[:, 1] + off
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    return np.stack([(gcx - pcx) / pw, (gcy - pcy) / ph,
                     np.log(gw / pw), np.log(gh / ph)], axis=1)


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=False,
                      name=None):
    """Sample RPN anchors (rpn_target_assign_op.cc:214).  Single image.
    Returns (loc_index, score_index, tgt_bbox, tgt_label) flat tensors.
    use_random=False takes deterministic prefixes (the reference's test
    mode)."""
    anchors = _np(anchor_box).reshape(-1, 4)
    gt = _np(gt_boxes).reshape(-1, 4)
    A = anchors.shape[0]
    # straddle filter (reference rpn_target_assign_op.cc:99-119):
    # with straddle_thresh >= 0 only anchors inside the image (within
    # the threshold) are eligible; the rest keep label -1
    inside = np.ones(A, bool)
    if im_info is not None and rpn_straddle_thresh >= 0:
        info = _np(im_info).reshape(-1)
        ih, iw = float(info[0]), float(info[1])
        st = float(rpn_straddle_thresh)
        inside = ((anchors[:, 0] >= -st) & (anchors[:, 1] >= -st) &
                  (anchors[:, 2] < iw + st) & (anchors[:, 3] < ih + st))
    iou = _iou_np(anchors, gt)                     # [A, G]
    iou[~inside] = 0.0
    max_per_anchor = iou.max(axis=1)
    argmax_per_anchor = iou.argmax(axis=1)
    labels = np.full(A, -1, np.int64)
    # positives: best anchor per gt + anchors above positive_overlap
    best_per_gt = np.where(inside.any(), iou.argmax(axis=0), 0)
    labels[best_per_gt] = 1
    labels[~inside] = -1
    labels[max_per_anchor >= rpn_positive_overlap] = 1
    labels[(labels != 1) & (max_per_anchor < rpn_negative_overlap)] = 0
    labels[~inside] = -1
    fg_cnt = int(rpn_batch_size_per_im * rpn_fg_fraction)
    fg_idx = np.where(labels == 1)[0]
    if fg_idx.size > fg_cnt:
        disable = fg_idx[fg_cnt:] if not use_random else \
            np.random.choice(fg_idx, fg_idx.size - fg_cnt, replace=False)
        labels[disable] = -1
        fg_idx = np.where(labels == 1)[0]
    bg_cnt = rpn_batch_size_per_im - fg_idx.size
    bg_idx = np.where(labels == 0)[0]
    if bg_idx.size > bg_cnt:
        disable = bg_idx[bg_cnt:] if not use_random else \
            np.random.choice(bg_idx, bg_idx.size - bg_cnt, replace=False)
        labels[disable] = -1
    loc_index = np.where(labels == 1)[0]
    score_index = np.where(labels >= 0)[0]
    tgt_bbox = _box_encode_np(anchors[loc_index],
                              gt[argmax_per_anchor[loc_index]])
    tgt_label = labels[score_index].astype(np.int32)
    return (Tensor(jnp.asarray(loc_index.astype(np.int32))),
            Tensor(jnp.asarray(score_index.astype(np.int32))),
            Tensor(jnp.asarray(tgt_bbox.astype(np.float32))),
            Tensor(jnp.asarray(tgt_label.reshape(-1, 1))))


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4,
                            name=None):
    """Focal-loss target assignment (rpn_target_assign_op.cc:578): all
    anchors keep labels (no sampling); returns fg_num too."""
    anchors = _np(anchor_box).reshape(-1, 4)
    gt = _np(gt_boxes).reshape(-1, 4)
    gl = _np(gt_labels).reshape(-1)
    A = anchors.shape[0]
    iou = _iou_np(anchors, gt)
    max_pa = iou.max(axis=1)
    arg_pa = iou.argmax(axis=1)
    labels = np.full(A, -1, np.int64)
    labels[iou.argmax(axis=0)] = 1
    labels[max_pa >= positive_overlap] = 1
    labels[(labels != 1) & (max_pa < negative_overlap)] = 0
    loc_index = np.where(labels == 1)[0]
    score_index = np.where(labels >= 0)[0]
    tgt_bbox = _box_encode_np(anchors[loc_index], gt[arg_pa[loc_index]])
    # class target: gt label for positives, 0 for negatives
    tgt_label = np.zeros(score_index.size, np.int32)
    pos_mask = labels[score_index] == 1
    tgt_label[pos_mask] = gl[arg_pa[score_index[pos_mask]]].astype(np.int32)
    fg_num = np.asarray([int((labels == 1).sum()) + 1], np.int32)
    return (Tensor(jnp.asarray(loc_index.astype(np.int32))),
            Tensor(jnp.asarray(score_index.astype(np.int32))),
            Tensor(jnp.asarray(tgt_bbox.astype(np.float32))),
            Tensor(jnp.asarray(tgt_label.reshape(-1, 1))),
            Tensor(jnp.asarray(fg_num)))


def retinanet_detection_output(bboxes, scores, im_info, score_threshold=0.05,
                               nms_top_k=1000, keep_top_k=100,
                               nms_threshold=0.3, nms_eta=1.0, name=None):
    """Decode-free RetinaNet head output collection
    (retinanet_detection_output_op.cc:154): per-FPN-level top-k +
    threshold, then cross-level multiclass NMS.  bboxes/scores are lists
    of [N, Ai, 4] / [N, Ai, C] per level (already decoded boxes)."""
    N = _np(bboxes[0]).shape[0]
    all_rows, nums = [], []
    for n in range(N):
        cand_boxes, cand_scores, cand_cls = [], [], []
        for lvl in range(len(bboxes)):
            bx = _np(bboxes[lvl])[n]               # [A,4]
            sc = _np(scores[lvl])[n]               # [A,C]
            flat = sc.reshape(-1)
            sel = np.where(flat > score_threshold)[0]
            if sel.size > nms_top_k:
                sel = sel[np.argsort(-flat[sel], kind="stable")[:nms_top_k]]
            a_idx, c_idx = np.divmod(sel, sc.shape[1])
            cand_boxes.append(bx[a_idx])
            cand_scores.append(flat[sel])
            cand_cls.append(c_idx)
        if not cand_boxes:
            nums.append(0)
            continue
        cb = np.concatenate(cand_boxes)
        cs = np.concatenate(cand_scores)
        cc = np.concatenate(cand_cls)
        rows = []
        for c in np.unique(cc):
            m = cc == c
            keep = _nms_kernel(cb[m], cs[m], nms_threshold, nms_eta,
                               normalized=False)
            idxs = np.where(m)[0]
            for k in keep:
                rows.append((float(cs[idxs[k]]), int(c) + 1, cb[idxs[k]]))
        rows.sort(key=lambda t: -t[0])
        rows = rows[:keep_top_k]
        nums.append(len(rows))
        for s, c, bx in rows:
            all_rows.append([c, s] + list(bx))
    out = np.asarray(all_rows, np.float32).reshape(-1, 6)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(
        np.asarray(nums, np.int32)))


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=False,
                             is_cls_agnostic=False, name=None):
    """Sample 2nd-stage RoIs + regression targets
    (generate_proposal_labels_op.cc:64).  Single image.  Returns (rois,
    labels_int32, bbox_targets, bbox_inside_weights,
    bbox_outside_weights)."""
    rois = _np(rpn_rois).reshape(-1, 4)
    gt = _np(gt_boxes).reshape(-1, 4)
    gc = _np(gt_classes).reshape(-1)
    # gt boxes join the proposal pool (reference appends them)
    rois = np.concatenate([rois, gt], axis=0)
    iou = _iou_np(rois, gt)
    max_iou = iou.max(axis=1)
    arg_iou = iou.argmax(axis=1)
    fg = np.where(max_iou >= fg_thresh)[0]
    bg = np.where((max_iou < bg_thresh_hi) & (max_iou >= bg_thresh_lo))[0]
    fg_cnt = min(int(batch_size_per_im * fg_fraction), fg.size)
    if use_random and fg.size > fg_cnt:
        fg = np.random.choice(fg, fg_cnt, replace=False)
    else:
        fg = fg[:fg_cnt]
    bg_cnt = min(batch_size_per_im - fg_cnt, bg.size)
    if use_random and bg.size > bg_cnt:
        bg = np.random.choice(bg, bg_cnt, replace=False)
    else:
        bg = bg[:bg_cnt]
    keep = np.concatenate([fg, bg])
    labels = np.zeros(keep.size, np.int32)
    labels[:fg.size] = gc[arg_iou[fg]].astype(np.int32)
    out_rois = rois[keep]
    # per-class regression targets
    weights = np.asarray(bbox_reg_weights, np.float64)
    tgt = np.zeros((keep.size, 4 * class_nums), np.float32)
    inw = np.zeros_like(tgt)
    deltas = _box_encode_np(rois[fg], gt[arg_iou[fg]]) / weights
    for i, cls in enumerate(labels[:fg.size]):
        c = 1 if is_cls_agnostic else int(cls)
        tgt[i, 4 * c:4 * c + 4] = deltas[i]
        inw[i, 4 * c:4 * c + 4] = 1.0
    outw = (inw > 0).astype(np.float32)
    return (Tensor(jnp.asarray(out_rois.astype(np.float32))),
            Tensor(jnp.asarray(labels.reshape(-1, 1))),
            Tensor(jnp.asarray(tgt)),
            Tensor(jnp.asarray(inw)),
            Tensor(jnp.asarray(outw)))


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution, name=None):
    """Mask targets for Mask R-CNN (generate_mask_labels_op.cc:82).
    gt_segms: list (per gt) of polygons (list of [x0,y0,x1,y1,...]) OR a
    [G, H, W] binary mask array.  Rasterizes polygons with PIL and
    crop-resizes each fg roi's matched gt mask to resolution**2.
    Returns (mask_rois, roi_has_mask_int32, mask_int32)."""
    rois_np = _np(rois).reshape(-1, 4)
    labels = _np(labels_int32).reshape(-1)
    gt = None
    info = _np(im_info).reshape(-1)
    H, W = int(round(float(info[0]))), int(round(float(info[1])))
    if isinstance(gt_segms, (list, tuple)):
        from PIL import Image, ImageDraw
        masks = []
        for polys in gt_segms:
            img = Image.new("L", (W, H), 0)
            drw = ImageDraw.Draw(img)
            for poly in (polys if isinstance(polys[0], (list, tuple,
                                                        np.ndarray))
                         else [polys]):
                drw.polygon([float(v) for v in np.asarray(poly).reshape(-1)],
                            fill=1)
            masks.append(np.asarray(img, np.uint8))
        gt = np.stack(masks) if masks else np.zeros((0, H, W), np.uint8)
    else:
        gt = _np(gt_segms).astype(np.uint8)
    gt_boxes_from_masks = []
    for m in gt:
        ys, xs = np.where(m > 0)
        if ys.size == 0:
            gt_boxes_from_masks.append([0, 0, 0, 0])
        else:
            gt_boxes_from_masks.append([xs.min(), ys.min(),
                                        xs.max(), ys.max()])
    gtb = np.asarray(gt_boxes_from_masks, np.float64).reshape(-1, 4)
    fg = np.where(labels > 0)[0]
    mask_rois, has_mask, mask_tgts = [], [], []
    for i in fg:
        roi = rois_np[i]
        iou = _iou_np(roi[None], gtb)[0]
        g = int(iou.argmax()) if iou.size else 0
        x1, y1, x2, y2 = [int(round(v)) for v in roi]
        x2, y2 = max(x2, x1 + 1), max(y2, y1 + 1)
        crop = gt[g][max(y1, 0):y2, max(x1, 0):x2] if gt.size else \
            np.zeros((1, 1), np.uint8)
        if crop.size == 0:
            crop = np.zeros((1, 1), np.uint8)
        from PIL import Image
        m = np.asarray(Image.fromarray(crop * 255).resize(
            (resolution, resolution), Image.NEAREST)) > 127
        cls = int(labels[i])
        tgt = np.full((num_classes, resolution, resolution), -1, np.int32)
        tgt[cls] = m.astype(np.int32)
        mask_rois.append(roi)
        has_mask.append(int(i))
        mask_tgts.append(tgt.reshape(-1))
    mask_rois = (np.asarray(mask_rois, np.float32).reshape(-1, 4))
    return (Tensor(jnp.asarray(mask_rois)),
            Tensor(jnp.asarray(np.asarray(has_mask, np.int32)
                               .reshape(-1, 1))),
            Tensor(jnp.asarray(
                np.asarray(mask_tgts, np.int32).reshape(len(mask_tgts), -1)
                if mask_tgts else
                np.zeros((0, num_classes * resolution ** 2), np.int32))))


def mine_hard_examples(cls_loss, loc_loss=None, match_indices=None,
                       match_dist=None, neg_pos_ratio=3.0,
                       neg_dist_threshold=0.5, sample_size=None,
                       mining_type="max_negative", name=None):
    """OHEM negative mining for SSD (mine_hard_examples_op.cc:54).
    cls_loss: [B, M]; match_indices: [B, M] (-1 = unmatched).
    Returns (neg_indices ragged-as-padded [B, max_neg] int32 with -1
    padding, updated_match_indices [B, M])."""
    cl = _np(cls_loss)
    if loc_loss is not None:
        cl = cl + _np(loc_loss)
    mi = _np(match_indices).astype(np.int64)
    md = _np(match_dist) if match_dist is not None else None
    B, M = mi.shape
    neg_lists = []
    upd = mi.copy()
    for b in range(B):
        pos = mi[b] >= 0
        n_pos = int(pos.sum())
        if mining_type == "max_negative":
            neg_cand = np.where(~pos)[0]
            if md is not None:
                neg_cand = neg_cand[md[b][neg_cand] < neg_dist_threshold]
            n_neg = int(n_pos * neg_pos_ratio)
            if sample_size is not None:
                n_neg = min(n_neg, int(sample_size))
            order = neg_cand[np.argsort(-cl[b][neg_cand], kind="stable")]
            sel = np.sort(order[:n_neg])
            neg_lists.append(sel)
        else:
            raise NotImplementedError(mining_type)
    width = max((len(s) for s in neg_lists), default=0)
    out = np.full((B, width), -1, np.int32)
    for b, s in enumerate(neg_lists):
        out[b, :len(s)] = s
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(
        upd.astype(np.int32)))


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_type="integral", name=None):
    """Detection mAP (detection_map_op.h:52).  detect_res rows
    (label, score, x1, y1, x2, y2); label rows
    (label, x1, y1, x2, y2[, difficult]).  Single image, or pass
    per-image lists.  Returns scalar mAP tensor."""
    det = _np(detect_res).reshape(-1, 6)
    gt = _np(label)
    gt = gt.reshape(-1, gt.shape[-1])
    has_diff = gt.shape[1] == 6
    aps = []
    for c in range(class_num):
        if c == background_label:
            continue
        gmask = gt[:, 0] == c
        gboxes = gt[gmask][:, 1:5]
        diff = (gt[gmask][:, 5].astype(bool) if has_diff
                else np.zeros(gmask.sum(), bool))
        npos = int((~diff).sum()) if not evaluate_difficult else \
            int(gmask.sum())
        dmask = det[:, 0] == c
        drows = det[dmask]
        if drows.shape[0] == 0:
            if npos > 0:
                aps.append(0.0)
            continue
        order = np.argsort(-drows[:, 1], kind="stable")
        drows = drows[order]
        matched = np.zeros(gboxes.shape[0], bool)
        tp = np.zeros(drows.shape[0])
        fp = np.zeros(drows.shape[0])
        for i, row in enumerate(drows):
            if gboxes.shape[0] == 0:
                fp[i] = 1
                continue
            iou = _iou_np(row[None, 2:6], gboxes, off=0.0)[0]
            j = int(iou.argmax())
            if iou[j] >= overlap_threshold and not matched[j]:
                if not evaluate_difficult and diff[j]:
                    continue    # ignore difficult matches entirely
                matched[j] = True
                tp[i] = 1
            else:
                fp[i] = 1
        if npos == 0:
            continue
        ctp = np.cumsum(tp)
        cfp = np.cumsum(fp)
        rec = ctp / npos
        prec = ctp / np.maximum(ctp + cfp, 1e-12)
        if ap_type == "integral":
            ap = 0.0
            prev_rec = 0.0
            for r, p in zip(rec, prec):
                ap += p * (r - prev_rec)
                prev_rec = r
        else:                    # 11point
            ap = 0.0
            for t in np.arange(0.0, 1.1, 0.1):
                pmax = prec[rec >= t].max() if (rec >= t).any() else 0.0
                ap += pmax / 11.0
        aps.append(float(ap))
    m = float(np.mean(aps)) if aps else 0.0
    return Tensor(jnp.asarray(np.float32(m)))


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip_value=4.135, name=None):
    """Decode per-class deltas and pick best-scoring class's box
    (box_decoder_and_assign_op.h:27).  target_box: [N, 4C];
    box_score: [N, C].  Returns (decode_box [N,4C],
    output_assign_box [N,4])."""
    pb = _np(prior_box).reshape(-1, 4)
    pv = _np(prior_box_var)
    tb = _np(target_box)
    sc = _np(box_score)
    N = pb.shape[0]
    C = sc.shape[1]
    pw = pb[:, 2] - pb[:, 0] + 1
    ph = pb[:, 3] - pb[:, 1] + 1
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2
    dec = np.zeros_like(tb)
    for c in range(C):
        d = tb[:, 4 * c:4 * c + 4]
        vx, vy, vw, vh = (pv[:, k] if pv.ndim == 2 else pv[k]
                          for k in range(4))
        cx = vx * d[:, 0] * pw + pcx
        cy = vy * d[:, 1] * ph + pcy
        w = np.exp(np.minimum(vw * d[:, 2], box_clip_value)) * pw
        h = np.exp(np.minimum(vh * d[:, 3], box_clip_value)) * ph
        dec[:, 4 * c + 0] = cx - w / 2
        dec[:, 4 * c + 1] = cy - h / 2
        dec[:, 4 * c + 2] = cx + w / 2 - 1
        dec[:, 4 * c + 3] = cy + h / 2 - 1
    best = sc.argmax(axis=1)
    assign = dec.reshape(N, C, 4)[np.arange(N), best]
    return (Tensor(jnp.asarray(dec.astype(np.float32))),
            Tensor(jnp.asarray(assign.astype(np.float32))))

"""MobileNetV1 (reference python/paddle/vision/models/mobilenetv1.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["MobileNetV1", "mobilenet_v1"]


class _ConvBNReLU(nn.Layer):
    def __init__(self, in_c, out_c, k, stride=1, padding=0, groups=1):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                              groups=groups, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.relu(self.bn(self.conv(x)))


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride, scale=1.0):
        super().__init__()
        in_c = int(in_c * scale)
        out_c = int(out_c * scale)
        self.dw = _ConvBNReLU(in_c, in_c, 3, stride, 1, groups=in_c)
        self.pw = _ConvBNReLU(in_c, out_c, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _ConvBNReLU(3, int(32 * scale), 3, 2, 1)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        blocks = [_DepthwiseSeparable(i, o, s, scale) for i, o, s in cfg]
        self.blocks = nn.Sequential(*blocks)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)

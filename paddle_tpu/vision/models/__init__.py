"""paddle.vision.models (reference python/paddle/vision/models/)."""
from .lenet import LeNet  # noqa: F401
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,  # noqa: F401
                     resnet152, wide_resnet50_2, wide_resnet101_2)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .mobilenetv1 import MobileNetV1, mobilenet_v1  # noqa: F401
from .mobilenetv2 import MobileNetV2, mobilenet_v2  # noqa: F401
from .alexnet import AlexNet, alexnet  # noqa: F401
from .squeezenet import SqueezeNet, squeezenet1_0, squeezenet1_1  # noqa: F401
from .googlenet import GoogLeNet, googlenet  # noqa: F401
from .densenet import (DenseNet, densenet121, densenet161, densenet169,  # noqa: F401
                       densenet201, densenet264)
from .inceptionv3 import InceptionV3, inception_v3  # noqa: F401
from .resnext import (ResNeXt, resnext50_32x4d, resnext50_64x4d,  # noqa: F401
                      resnext101_32x4d, resnext101_64x4d, resnext152_32x4d,
                      resnext152_64x4d)

"""DenseNet family (reference python/paddle/vision/models/densenet.py).

Dense blocks concatenate every prior feature map; on TPU the concats are
pure layout ops XLA folds into the following 1x1 conv's MXU matmul.
The pre-activation bn->relu->conv blocks dispatch as one fused op via
``nn.functional.fused_conv_bn(pre_norm=True)`` behind ``FLAGS_fused_conv``.
"""
from __future__ import annotations

from ... import ops as P
from ... import nn
from ...nn import functional as F

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_ARCH = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class _DenseLayer(nn.Layer):
    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(in_c)
        self.conv1 = nn.Conv2D(in_c, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = nn.Dropout(dropout) if dropout else None
        self.relu = nn.ReLU()

    def forward(self, x):
        out = F.fused_conv_bn(x, self.conv1, self.bn1, act="relu",
                              pre_norm=True)
        out = F.fused_conv_bn(out, self.conv2, self.bn2, act="relu",
                              pre_norm=True)
        if self.dropout is not None:
            out = self.dropout(out)
        return P.concat([x, out], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, num_layers, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(in_c + i * growth_rate, growth_rate, bn_size, dropout)
            for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.bn = nn.BatchNorm2D(in_c)
        self.conv = nn.Conv2D(in_c, out_c, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.pool(F.fused_conv_bn(x, self.conv, self.bn,
                                         act="relu", pre_norm=True))


class DenseNet(nn.Layer):
    """DenseNet model (reference ``vision/models/densenet.py`` DenseNet)."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _ARCH:
            raise ValueError(f"layers must be one of {sorted(_ARCH)}")
        num_init, growth_rate, block_cfg = _ARCH[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool

        self.conv0 = nn.Conv2D(3, num_init, 7, stride=2, padding=3,
                               bias_attr=False)
        self.bn0 = nn.BatchNorm2D(num_init)
        self.relu = nn.ReLU()
        self.pool0 = nn.MaxPool2D(3, stride=2, padding=1)

        blocks, chans = [], num_init
        for i, n in enumerate(block_cfg):
            blocks.append(_DenseBlock(n, chans, growth_rate, bn_size,
                                      dropout))
            chans += n * growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(chans, chans // 2))
                chans //= 2
        self.blocks = nn.LayerList(blocks)
        self.bn_last = nn.BatchNorm2D(chans)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(chans, num_classes)

    def forward(self, x):
        x = self.pool0(F.fused_conv_bn(x, self.conv0, self.bn0,
                                       act="relu"))
        for b in self.blocks:
            x = b(x)
        x = self.relu(self.bn_last(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = P.flatten(x, 1)
            x = self.classifier(x)
        return x


def _densenet(layers, pretrained=False, **kwargs):
    model = DenseNet(layers=layers, **kwargs)
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require paddle.hub connectivity")
    return model


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)

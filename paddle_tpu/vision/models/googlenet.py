"""GoogLeNet (reference python/paddle/vision/models/googlenet.py)."""
from __future__ import annotations

from ... import nn
from ... import ops
from ...nn import functional as F


def _hooked(layer):
    return layer._forward_pre_hooks or layer._forward_post_hooks


def _fused_seq(x, seq):
    """Run a Sequential through the fused dispatch, deriving the layout
    from its live members instead of hard-coded indices: each Conv2D
    immediately followed by ReLU collapses into one fused conv+relu;
    everything else (pools, PTQ-swapped quantized convs, ...) runs
    as-is in order.  Registered forward hooks stay an observable
    contract: a hooked container runs as a plain Sequential, and a
    hooked ReLU member keeps its pair on the module path (hooked convs
    already force the eager fallback inside ``fused_conv_bn``)."""
    if _hooked(seq):
        return seq(x)
    subs = list(seq._sub_layers.values())
    i = 0
    while i < len(subs):
        m = subs[i]
        if isinstance(m, nn.Conv2D) and i + 1 < len(subs) and \
                isinstance(subs[i + 1], nn.ReLU) and \
                not _hooked(subs[i + 1]):
            x = F.fused_conv_bn(x, m, None, act="relu")
            i += 2
        else:
            x = m(x)
            i += 1
    return x

__all__ = ["GoogLeNet", "googlenet"]


class _Inception(nn.Layer):
    def __init__(self, in_c, c1, c2r, c2, c3r, c3, c4):
        super().__init__()
        self.b1 = nn.Sequential(nn.Conv2D(in_c, c1, 1), nn.ReLU())
        self.b2 = nn.Sequential(nn.Conv2D(in_c, c2r, 1), nn.ReLU(),
                                nn.Conv2D(c2r, c2, 3, padding=1), nn.ReLU())
        self.b3 = nn.Sequential(nn.Conv2D(in_c, c3r, 1), nn.ReLU(),
                                nn.Conv2D(c3r, c3, 5, padding=2), nn.ReLU())
        self.b4 = nn.Sequential(nn.MaxPool2D(3, 1, padding=1),
                                nn.Conv2D(in_c, c4, 1), nn.ReLU())

    def forward(self, x):
        return ops.concat([_fused_seq(x, b) for b in
                           (self.b1, self.b2, self.b3, self.b4)], axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2D(3, 64, 7, stride=2, padding=3), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1),
            nn.Conv2D(64, 64, 1), nn.ReLU(),
            nn.Conv2D(64, 192, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, 2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, 2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.avgpool = nn.AdaptiveAvgPool2D(1)
        self.dropout = nn.Dropout(0.2)
        self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = _fused_seq(x, self.stem)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.pool4(self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x))))))
        x = self.i5b(self.i5a(x))
        x = self.avgpool(x).flatten(1)
        return self.fc(self.dropout(x))


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)

"""ResNeXt family (reference python/paddle/vision/models/resnext.py).

Grouped 3x3 convolutions (cardinality) — XLA lowers grouped conv to a
batched MXU contraction, so cardinality is free on TPU.
"""
from __future__ import annotations

from ... import nn
from .resnet import ResNet, BottleneckBlock

__all__ = ["ResNeXt", "resnext50_32x4d", "resnext50_64x4d",
           "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
           "resnext152_64x4d"]

_DEPTHS = (50, 101, 152)  # stage counts live in ResNet's layer_cfg


class ResNeXt(ResNet):
    """ResNeXt = ResNet bottleneck with cardinality groups
    (reference ``vision/models/resnext.py`` ResNeXt)."""

    def __init__(self, depth=50, cardinality=32, width=4, num_classes=1000,
                 with_pool=True):
        if depth not in _DEPTHS:
            raise ValueError(f"depth must be one of {_DEPTHS}")
        # ResNet's bottleneck width = planes * (base_width/64) * groups, so
        # passing width=4, groups=32 gives the 32x4d stage widths
        # (128/256/512/1024).
        super().__init__(BottleneckBlock, depth=depth, width=width,
                         num_classes=num_classes, with_pool=with_pool,
                         groups=cardinality)
        self.cardinality = cardinality


def _resnext(depth, cardinality, width, pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require paddle.hub connectivity")
    return ResNeXt(depth=depth, cardinality=cardinality, width=width,
                   **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return _resnext(50, 32, 4, pretrained, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return _resnext(50, 64, 4, pretrained, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return _resnext(101, 32, 4, pretrained, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _resnext(101, 64, 4, pretrained, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return _resnext(152, 32, 4, pretrained, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return _resnext(152, 64, 4, pretrained, **kwargs)

"""Inception v3 (reference python/paddle/vision/models/inceptionv3.py).

The factorized 1xN/Nx1 convolutions map to skinny MXU matmuls; XLA fuses
the branch concats into the consumers.
"""
from __future__ import annotations

from ... import ops as P
from ... import nn
from ...nn import functional as F

__all__ = ["InceptionV3", "inception_v3"]


class _ConvBN(nn.Layer):
    def __init__(self, in_c, out_c, kernel, stride=1, padding=0):
        super().__init__()
        self.conv = nn.Conv2D(in_c, out_c, kernel, stride=stride,
                              padding=padding, bias_attr=False)
        self.bn = nn.BatchNorm2D(out_c)
        self.relu = nn.ReLU()

    def forward(self, x):
        return F.fused_conv_bn(x, self.conv, self.bn, act="relu")


class _InceptionA(nn.Layer):
    def __init__(self, in_c, pool_features):
        super().__init__()
        self.b1 = _ConvBN(in_c, 64, 1)
        self.b5_1 = _ConvBN(in_c, 48, 1)
        self.b5_2 = _ConvBN(48, 64, 5, padding=2)
        self.b3_1 = _ConvBN(in_c, 64, 1)
        self.b3_2 = _ConvBN(64, 96, 3, padding=1)
        self.b3_3 = _ConvBN(96, 96, 3, padding=1)
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBN(in_c, pool_features, 1)

    def forward(self, x):
        return P.concat([
            self.b1(x),
            self.b5_2(self.b5_1(x)),
            self.b3_3(self.b3_2(self.b3_1(x))),
            self.bp(self.pool(x))], axis=1)


class _InceptionB(nn.Layer):
    """Grid reduction 35x35 -> 17x17."""

    def __init__(self, in_c):
        super().__init__()
        self.b3 = _ConvBN(in_c, 384, 3, stride=2)
        self.bd_1 = _ConvBN(in_c, 64, 1)
        self.bd_2 = _ConvBN(64, 96, 3, padding=1)
        self.bd_3 = _ConvBN(96, 96, 3, stride=2)
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return P.concat([
            self.b3(x),
            self.bd_3(self.bd_2(self.bd_1(x))),
            self.pool(x)], axis=1)


class _InceptionC(nn.Layer):
    def __init__(self, in_c, channels_7x7):
        super().__init__()
        c7 = channels_7x7
        self.b1 = _ConvBN(in_c, 192, 1)
        self.b7_1 = _ConvBN(in_c, c7, 1)
        self.b7_2 = _ConvBN(c7, c7, (1, 7), padding=(0, 3))
        self.b7_3 = _ConvBN(c7, 192, (7, 1), padding=(3, 0))
        self.b7d_1 = _ConvBN(in_c, c7, 1)
        self.b7d_2 = _ConvBN(c7, c7, (7, 1), padding=(3, 0))
        self.b7d_3 = _ConvBN(c7, c7, (1, 7), padding=(0, 3))
        self.b7d_4 = _ConvBN(c7, c7, (7, 1), padding=(3, 0))
        self.b7d_5 = _ConvBN(c7, 192, (1, 7), padding=(0, 3))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBN(in_c, 192, 1)

    def forward(self, x):
        return P.concat([
            self.b1(x),
            self.b7_3(self.b7_2(self.b7_1(x))),
            self.b7d_5(self.b7d_4(self.b7d_3(self.b7d_2(self.b7d_1(x))))),
            self.bp(self.pool(x))], axis=1)


class _InceptionD(nn.Layer):
    """Grid reduction 17x17 -> 8x8."""

    def __init__(self, in_c):
        super().__init__()
        self.b3_1 = _ConvBN(in_c, 192, 1)
        self.b3_2 = _ConvBN(192, 320, 3, stride=2)
        self.b7_1 = _ConvBN(in_c, 192, 1)
        self.b7_2 = _ConvBN(192, 192, (1, 7), padding=(0, 3))
        self.b7_3 = _ConvBN(192, 192, (7, 1), padding=(3, 0))
        self.b7_4 = _ConvBN(192, 192, 3, stride=2)
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        return P.concat([
            self.b3_2(self.b3_1(x)),
            self.b7_4(self.b7_3(self.b7_2(self.b7_1(x)))),
            self.pool(x)], axis=1)


class _InceptionE(nn.Layer):
    def __init__(self, in_c):
        super().__init__()
        self.b1 = _ConvBN(in_c, 320, 1)
        self.b3_1 = _ConvBN(in_c, 384, 1)
        self.b3_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.bd_1 = _ConvBN(in_c, 448, 1)
        self.bd_2 = _ConvBN(448, 384, 3, padding=1)
        self.bd_3a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.bd_3b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.pool = nn.AvgPool2D(3, stride=1, padding=1)
        self.bp = _ConvBN(in_c, 192, 1)

    def forward(self, x):
        b3 = self.b3_1(x)
        bd = self.bd_2(self.bd_1(x))
        return P.concat([
            self.b1(x),
            P.concat([self.b3_2a(b3), self.b3_2b(b3)], axis=1),
            P.concat([self.bd_3a(bd), self.bd_3b(bd)], axis=1),
            self.bp(self.pool(x))], axis=1)


class InceptionV3(nn.Layer):
    """Inception v3 (reference ``vision/models/inceptionv3.py`` InceptionV3)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = nn.Sequential(
            _ConvBN(3, 32, 3, stride=2),
            _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1),
            nn.MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1),
            _ConvBN(80, 192, 3),
            nn.MaxPool2D(3, stride=2),
        )
        self.blocks = nn.Sequential(
            _InceptionA(192, 32),
            _InceptionA(256, 64),
            _InceptionA(288, 64),
            _InceptionB(288),
            _InceptionC(768, 128),
            _InceptionC(768, 160),
            _InceptionC(768, 160),
            _InceptionC(768, 192),
            _InceptionD(768),
            _InceptionE(1280),
            _InceptionE(2048),
        )
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(2048, num_classes)

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.dropout(P.flatten(x, 1))
            x = self.fc(x)
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights require paddle.hub connectivity")
    return InceptionV3(**kwargs)

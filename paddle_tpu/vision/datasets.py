"""paddle.vision.datasets (reference python/paddle/vision/datasets/).

Zero-egress image: real downloads are unavailable; MNIST/Cifar provide a
deterministic synthetic fallback with the exact shapes/dtypes so training
pipelines exercise end-to-end (BASELINE configs use synthetic batches
anyway for throughput measurement).
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "Flowers", "VOC2012"]


class _SyntheticImageDataset(Dataset):
    _shape = (1, 28, 28)
    _nclass = 10
    _n = 60000

    def __init__(self, mode="train", transform=None, backend=None,
                 download=True, image_path=None, label_path=None,
                 data_file=None):
        self.mode = mode
        self.transform = transform
        n = self._n if mode == "train" else self._n // 6
        rng = np.random.RandomState(0 if mode == "train" else 1)
        # small deterministic pool re-indexed to the advertised length:
        # keeps memory bounded while giving stable per-index samples
        pool = 2048
        self._images = rng.randint(
            0, 256, size=(pool,) + tuple(self._shape)).astype("uint8")
        self._labels = rng.randint(0, self._nclass, size=(pool,)).astype(
            "int64")
        self._len = n
        self._pool = pool

    def __getitem__(self, idx):
        img = self._images[idx % self._pool].astype("float32") / 255.0
        label = self._labels[idx % self._pool]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        return img, np.asarray([label], dtype="int64")

    def __len__(self):
        return self._len


class MNIST(_SyntheticImageDataset):
    _shape = (1, 28, 28)
    _nclass = 10
    _n = 60000


class FashionMNIST(MNIST):
    pass


class Cifar10(_SyntheticImageDataset):
    _shape = (3, 32, 32)
    _nclass = 10
    _n = 50000


class Cifar100(Cifar10):
    _nclass = 100


class DatasetFolder(Dataset):
    """Directory-of-class-folders loader (reference folder.py)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or (".npy",)
        self.samples = []
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(d, fname),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        return np.load(path)

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        extensions = extensions or (".npy",)
        self.samples = [os.path.join(root, f) for f in sorted(os.listdir(root))
                        if f.lower().endswith(tuple(extensions))]

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(_SyntheticImageDataset):
    """reference vision/datasets/flowers.py (102 classes, 3x224x224)."""
    _shape = (3, 64, 64)   # reduced synthetic resolution; same fields
    _nclass = 102
    _n = 6149


class VOC2012(_SyntheticImageDataset):
    """reference vision/datasets/voc2012.py: (image, segmentation mask)."""
    _shape = (3, 64, 64)
    _nclass = 21
    _n = 2913

    def __getitem__(self, idx):
        # image path shared with the base class; NOTE: transforms are
        # image-only here, so use geometry-preserving transforms (paired
        # image+mask augmentation is the caller's job)
        img, _ = super().__getitem__(idx)
        rng = np.random.RandomState(idx % self._pool)
        mask = rng.randint(0, self._nclass,
                           size=self._shape[1:]).astype("int64")
        return img, mask

"""paddle.vision.transforms (reference python/paddle/vision/transforms/).

Numpy/host-side image transforms feeding the DataLoader.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop",
           "to_tensor", "normalize", "resize", "hflip", "vflip"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def to_tensor(img, data_format="CHW"):
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    if a.dtype == np.uint8:
        a = a.astype("float32") / 255.0
    else:
        a = a.astype("float32")
    if data_format == "CHW":
        a = a.transpose(2, 0, 1)
    return a


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, dtype="float32")
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if data_format == "CHW":
        return (a - mean[:, None, None]) / std[:, None, None]
    return (a - mean) / std


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std, self.data_format = mean, std, data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    a = np.asarray(img)
    import jax
    import jax.numpy as jnp
    if isinstance(size, int):
        h, w = a.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    out_shape = (size[0], size[1]) + a.shape[2:]
    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic"}.get(interpolation, "linear")
    return np.asarray(jax.image.resize(jnp.asarray(a, jnp.float32),
                                       out_shape, method=method))


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        if self.padding:
            p = self.padding
            a = np.pad(a, [(p, p), (p, p)] + [(0, 0)] * (a.ndim - 2))
        h, w = a.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return a[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return a[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            tw = int(round((target * ar) ** 0.5))
            th = int(round((target / ar) ** 0.5))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return resize(a[i:i + th, j:j + tw], self.size,
                              self.interpolation)
        return resize(CenterCrop(min(h, w))(a), self.size, self.interpolation)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if random.random() < self.prob else np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if random.random() < self.prob else np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        return a.transpose(self.order)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        a = np.asarray(img, dtype="float32")
        alpha = 1 + random.uniform(-self.value, self.value)
        return np.clip(a * alpha, 0, 255 if a.max() > 1 else 1.0)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        return np.pad(a, [(p[1], p[3]), (p[0], p[2])] +
                      [(0, 0)] * (a.ndim - 2))

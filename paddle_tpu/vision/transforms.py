"""paddle.vision.transforms (reference python/paddle/vision/transforms/).

Numpy/host-side image transforms feeding the DataLoader.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop",
           "BaseTransform", "Grayscale", "RandomRotation",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "ColorJitter", "to_tensor", "normalize", "resize", "hflip",
           "vflip", "crop", "center_crop", "pad", "rotate", "to_grayscale",
           "adjust_brightness", "adjust_contrast", "adjust_hue"]


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


def to_tensor(img, data_format="CHW"):
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    if a.dtype == np.uint8:
        a = a.astype("float32") / 255.0
    else:
        a = a.astype("float32")
    if data_format == "CHW":
        a = a.transpose(2, 0, 1)
    return a


class ToTensor:
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def __call__(self, img):
        return to_tensor(img, self.data_format)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    a = np.asarray(img, dtype="float32")
    mean = np.asarray(mean, dtype="float32")
    std = np.asarray(std, dtype="float32")
    if data_format == "CHW":
        return (a - mean[:, None, None]) / std[:, None, None]
    return (a - mean) / std


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std, self.data_format = mean, std, data_format

    def __call__(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


def resize(img, size, interpolation="bilinear"):
    a = np.asarray(img)
    import jax
    import jax.numpy as jnp
    if isinstance(size, int):
        h, w = a.shape[:2]
        if h < w:
            size = (size, int(size * w / h))
        else:
            size = (int(size * h / w), size)
    out_shape = (size[0], size[1]) + a.shape[2:]
    method = {"bilinear": "linear", "nearest": "nearest",
              "bicubic": "cubic"}.get(interpolation, "linear")
    return np.asarray(jax.image.resize(jnp.asarray(a, jnp.float32),
                                       out_shape, method=method))


class Resize:
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def __call__(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        if self.padding:
            p = self.padding
            a = np.pad(a, [(p, p), (p, p)] + [(0, 0)] * (a.ndim - 2))
        h, w = a.shape[:2]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return a[i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return a[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def __call__(self, img):
        a = np.asarray(img)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            tw = int(round((target * ar) ** 0.5))
            th = int(round((target / ar) ** 0.5))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return resize(a[i:i + th, j:j + tw], self.size,
                              self.interpolation)
        return resize(CenterCrop(min(h, w))(a), self.size, self.interpolation)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def vflip(img):
    return np.asarray(img)[::-1].copy()


class RandomHorizontalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        return hflip(img) if random.random() < self.prob else np.asarray(img)


class RandomVerticalFlip:
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if random.random() < self.prob else np.asarray(img)


class Transpose:
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def __call__(self, img):
        a = np.asarray(img)
        if a.ndim == 2:
            a = a[:, :, None]
        return a.transpose(self.order)


class BrightnessTransform:
    def __init__(self, value, keys=None):
        self.value = value

    def __call__(self, img):
        alpha = 1 + random.uniform(-self.value, self.value)
        return adjust_brightness(img, alpha)


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding

    def __call__(self, img):
        a = np.asarray(img)
        p = self.padding
        if isinstance(p, int):
            p = (p, p, p, p)
        return np.pad(a, [(p[1], p[3]), (p[0], p[2])] +
                      [(0, 0)] * (a.ndim - 2))


# ---------------------------------------------------------------------------
# remaining functional ops + transforms (reference vision/transforms/)
# ---------------------------------------------------------------------------
def crop(img, top, left, height, width):
    """Crop an HWC ndarray (reference transforms.functional.crop)."""
    img = np.asarray(img)
    return img[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = np.asarray(img)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    h, w = img.shape[:2]
    top = max(0, (h - oh) // 2)
    left = max(0, (w - ow) // 2)
    return crop(img, top, left, oh, ow)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = np.asarray(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    width = [(pt, pb), (pl, pr)] + [(0, 0)] * (img.ndim - 2)
    mode = {"constant": "constant", "edge": "edge",
            "reflect": "reflect", "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, width, mode=mode, **kw)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate an HWC image by ``angle`` degrees counter-clockwise
    (inverse-warp with nearest/bilinear sampling)."""
    img = np.asarray(img)
    h, w = img.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    if expand:
        nh = int(abs(h * cos) + abs(w * sin) + 0.5)
        nw = int(abs(w * cos) + abs(h * sin) + 0.5)
    else:
        nh, nw = h, w
    ocy, ocx = (nh - 1) / 2.0, (nw - 1) / 2.0
    yy, xx = np.meshgrid(np.arange(nh), np.arange(nw), indexing="ij")
    # inverse map: output (y, x) -> source coords
    sy = (yy - ocy) * cos - (xx - ocx) * sin + cy
    sx = (yy - ocy) * sin + (xx - ocx) * cos + cx
    if interpolation == "bilinear":
        y0 = np.clip(np.floor(sy).astype(int), 0, h - 1)
        x0 = np.clip(np.floor(sx).astype(int), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        ly, lx = sy - y0, sx - x0
        ly = np.clip(ly, 0, 1)[..., None] if img.ndim == 3 else np.clip(ly, 0, 1)
        lx = np.clip(lx, 0, 1)[..., None] if img.ndim == 3 else np.clip(lx, 0, 1)
        out = (img[y0, x0] * (1 - ly) * (1 - lx) + img[y1, x0] * ly * (1 - lx)
               + img[y0, x1] * (1 - ly) * lx + img[y1, x1] * ly * lx)
    else:
        ys = np.clip(np.round(sy).astype(int), 0, h - 1)
        xs = np.clip(np.round(sx).astype(int), 0, w - 1)
        out = img[ys, xs]
    inside = (sy >= -0.5) & (sy <= h - 0.5) & (sx >= -0.5) & (sx <= w - 0.5)
    if img.ndim == 3:
        inside = inside[..., None]
    return np.where(inside, out, fill).astype(img.dtype)


def to_grayscale(img, num_output_channels=1):
    img = np.asarray(img).astype(np.float32)
    gray = img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114
    gray = gray[..., None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=-1)
    return gray


def _value_range(img):
    """Value ceiling from dtype: integer images are [0, 255], floats are
    [0, 1] (value-based guessing misclassifies dark uint8 frames)."""
    return 255.0 if np.issubdtype(np.asarray(img).dtype, np.integer) \
        else 1.0


def adjust_brightness(img, brightness_factor):
    orig = np.asarray(img).dtype
    hi = _value_range(img)
    img = np.asarray(img).astype(np.float32)
    return np.clip(img * brightness_factor, 0, hi).astype(orig)


def adjust_contrast(img, contrast_factor):
    orig = np.asarray(img).dtype
    hi = _value_range(img)
    img = np.asarray(img).astype(np.float32)
    mean = to_grayscale(img).mean()
    return np.clip(mean + contrast_factor * (img - mean), 0, hi).astype(orig)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5] turns) via HSV roundtrip."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    orig_dtype = np.asarray(img).dtype
    hi = _value_range(img)
    img = np.asarray(img).astype(np.float32)
    x = img / hi
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx, mn = x.max(-1), x.min(-1)
    diff = mx - mn + 1e-12
    h = np.zeros_like(mx)
    h = np.where(mx == r, ((g - b) / diff) % 6, h)
    h = np.where(mx == g, (b - r) / diff + 2, h)
    h = np.where(mx == b, (r - g) / diff + 4, h)
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0)
    v = mx
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    i = (i.astype(int) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return (out * hi).astype(orig_dtype)


class BaseTransform:
    """reference transforms.BaseTransform: keys-aware callable base."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, image):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, tuple):
            # elements beyond the declared keys pass through untouched
            keys = list(self.keys) + ["_"] * (len(inputs) - len(self.keys))
            return tuple(self._apply_image(i) if k == "image" else i
                         for i, k in zip(inputs, keys))
        return self._apply_image(inputs)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(
            degrees, (int, float)) else tuple(degrees)
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, **self.kw)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, factor)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        factor = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        gray = to_grayscale(img, 3)
        out = np.clip(gray + factor * (np.asarray(img, np.float32) - gray),
                      0, _value_range(img))
        return out.astype(np.asarray(img).dtype)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue (reference ColorJitter)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [t for t in (
            BrightnessTransform(brightness) if brightness else None,
            ContrastTransform(contrast) if contrast else None,
            SaturationTransform(saturation) if saturation else None,
            HueTransform(hue) if hue else None) if t is not None]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i](img)
        return img

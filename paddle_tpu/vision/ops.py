"""Detection / CV operators (``paddle.vision.ops`` parity).

Reference parity: ``python/paddle/vision/ops.py`` — yolo_box (:252),
yolo_loss (:42), deform_conv2d (:423) + DeformConv2D (:626),
psroi_pool (:911), roi_pool (:1022), roi_align (:1145) with their Layer
wrappers; backed by the ~40 detection kernels under
``paddle/fluid/operators/detection/``.

TPU-first: every op is dense, statically-shaped jnp — gathers/bilinear
sampling vectorize over boxes and lower to XLA gather/dot; there is no
per-box dynamic control flow (boxes_num selects by masking).  read_file/
decode_jpeg run host-side (PIL decode) like the reference's CPU kernels.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, to_tensor
from ..nn.layer_base import Layer

__all__ = ["yolo_box", "yolo_loss", "deform_conv2d", "DeformConv2D",
           "roi_pool", "RoIPool", "roi_align", "RoIAlign", "psroi_pool",
           "PSRoIPool"]


# ---------------------------------------------------------------------------
# YOLO
# ---------------------------------------------------------------------------
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode YOLOv3 head activations into boxes + scores
    (reference ``vision/ops.py:252`` / ``operators/detection/yolo_box_op``).

    x: (N, len(anchors)//2 * (5 + class_num), H, W); img_size: (N, 2) hw.
    Returns (boxes (N, H*W*na, 4) xyxy, scores (N, H*W*na, class_num)).
    """
    x, img_size = to_tensor(x), to_tensor(img_size)
    anchors = [int(a) for a in anchors]
    na = len(anchors) // 2

    def impl(a, imgs):
        N, C, H, W = a.shape
        a = a.reshape(N, na, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
        alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
        bx = (jax.nn.sigmoid(a[:, :, 0]) * alpha + beta + gx) / W
        by = (jax.nn.sigmoid(a[:, :, 1]) * alpha + beta + gy) / H
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]
        input_w = float(W * downsample_ratio)
        input_h = float(H * downsample_ratio)
        bw = jnp.exp(a[:, :, 2]) * aw / input_w
        bh = jnp.exp(a[:, :, 3]) * ah / input_h
        conf = jax.nn.sigmoid(a[:, :, 4])
        probs = jax.nn.sigmoid(a[:, :, 5:]) * conf[:, :, None]

        img_h = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        img_w = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * img_w
        y1 = (by - bh / 2) * img_h
        x2 = (bx + bw / 2) * img_w
        y2 = (by + bh / 2) * img_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0, img_w - 1)
            y1 = jnp.clip(y1, 0, img_h - 1)
            x2 = jnp.clip(x2, 0, img_w - 1)
            y2 = jnp.clip(y2, 0, img_h - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)        # (N,na,H,W,4)
        keep = (conf > conf_thresh).astype(boxes.dtype)
        boxes = boxes * keep[..., None]
        probs = probs * keep[:, :, None]
        boxes = boxes.reshape(N, na * H * W, 4)
        scores = jnp.moveaxis(probs, 2, -1).reshape(N, na * H * W,
                                                    class_num)
        return boxes, scores
    return dispatch("yolo_box", impl, (x, img_size), {})


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (reference ``vision/ops.py:42`` /
    ``yolov3_loss_op``): coordinate + objectness + class terms; boxes
    matched to the best anchor of this detection head."""
    x, gt_box, gt_label = to_tensor(x), to_tensor(gt_box), to_tensor(gt_label)
    anchors = [float(a) for a in anchors]
    mask = [int(m) for m in anchor_mask]
    na = len(mask)
    tensors = [x, gt_box, gt_label]
    has_score = gt_score is not None
    if has_score:
        tensors.append(to_tensor(gt_score))

    def impl(a, gb, gl, *rest):
        gs = rest[0] if has_score else None
        N, C, H, W = a.shape
        B = gb.shape[1]
        a = a.reshape(N, na, 5 + class_num, H, W)
        input_w = float(W * downsample_ratio)
        input_h = float(H * downsample_ratio)

        # gt boxes are (cx, cy, w, h) normalized to [0, 1]
        gx, gy, gw, gh = gb[..., 0], gb[..., 1], gb[..., 2], gb[..., 3]
        valid = (gw > 0).astype(a.dtype)                  # (N, B)
        gi = jnp.clip((gx * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gy * H).astype(jnp.int32), 0, H - 1)

        # best anchor over ALL anchors by IoU of (w, h) at the origin
        all_aw = jnp.asarray(anchors[0::2], a.dtype) / input_w
        all_ah = jnp.asarray(anchors[1::2], a.dtype) / input_h
        inter = jnp.minimum(gw[..., None], all_aw) * \
            jnp.minimum(gh[..., None], all_ah)
        union = gw[..., None] * gh[..., None] + all_aw * all_ah - inter
        best = jnp.argmax(inter / (union + 1e-9), axis=-1)  # (N, B)
        mask_arr = jnp.asarray(mask)
        in_head = (best[..., None] == mask_arr).any(-1).astype(a.dtype)
        an_idx = jnp.argmax((best[..., None] == mask_arr).astype(jnp.int32),
                            axis=-1)                        # (N, B)
        w_obj = valid * in_head
        if gs is not None:
            # per-box score weighting (mixup; reference gt_score path)
            w_obj = w_obj * gs.reshape(w_obj.shape)

        # target tx/ty/tw/th at matched cells
        aw = jnp.asarray(anchors[0::2], a.dtype)[an_idx] / input_w
        ah = jnp.asarray(anchors[1::2], a.dtype)[an_idx] / input_h
        tx = gx * W - gi.astype(a.dtype)
        ty = gy * H - gj.astype(a.dtype)
        tw = jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-9), 1e-9))
        th = jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-9), 1e-9))
        box_scale = 2.0 - gw * gh

        batch = jnp.arange(N)[:, None].repeat(B, 1)
        pred = a[batch, an_idx, :, gj, gi]                 # (N, B, 5+cls)
        px, py = jax.nn.sigmoid(pred[..., 0]), jax.nn.sigmoid(pred[..., 1])
        loss_xy = (jnp.square(px - tx) + jnp.square(py - ty)) * box_scale
        loss_wh = (jnp.square(pred[..., 2] - tw) +
                   jnp.square(pred[..., 3] - th)) * box_scale

        # objectness: positives at matched cells; negatives everywhere
        # else unless best IoU with any gt exceeds ignore_thresh
        obj_logit = a[:, :, 4]                             # (N,na,H,W)
        pos = jnp.zeros((N, na, H, W), a.dtype)
        pos = pos.at[batch, an_idx, gj, gi].max(w_obj)
        boxes_pred = _decode_all(a, anchors, mask, W, H, input_w, input_h,
                                 scale_x_y)
        iou = _iou_grid_vs_gt(boxes_pred, gb, valid)       # (N,na,H,W)
        ignore = (iou > ignore_thresh).astype(a.dtype) * (1 - pos)
        bce = jnp.maximum(obj_logit, 0) - obj_logit * pos + \
            jnp.log1p(jnp.exp(-jnp.abs(obj_logit)))
        loss_obj = jnp.sum(bce * (1 - ignore), axis=(1, 2, 3))

        smooth = 1.0 / max(class_num, 1) if use_label_smooth else 0.0
        tgt = jax.nn.one_hot(gl.reshape(N, B), class_num, dtype=a.dtype)
        tgt = tgt * (1 - smooth) + smooth / max(class_num, 1)
        cls_logit = pred[..., 5:]
        bce_c = jnp.maximum(cls_logit, 0) - cls_logit * tgt + \
            jnp.log1p(jnp.exp(-jnp.abs(cls_logit)))
        loss_cls = jnp.sum(bce_c, axis=-1)

        per_box = (loss_xy + loss_wh + loss_cls) * w_obj
        return jnp.sum(per_box, axis=1) + loss_obj         # (N,)
    return dispatch("yolo_loss", impl, tensors, {})


def _decode_all(a, anchors, mask, W, H, input_w, input_h, scale_x_y):
    na = len(mask)
    gx = jnp.arange(W, dtype=a.dtype)[None, None, None, :]
    gy = jnp.arange(H, dtype=a.dtype)[None, None, :, None]
    alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(a[:, :, 0]) * alpha + beta + gx) / W
    by = (jax.nn.sigmoid(a[:, :, 1]) * alpha + beta + gy) / H
    aw = jnp.asarray([anchors[2 * m] for m in mask],
                     a.dtype)[None, :, None, None] / input_w
    ah = jnp.asarray([anchors[2 * m + 1] for m in mask],
                     a.dtype)[None, :, None, None] / input_h
    bw = jnp.exp(jnp.clip(a[:, :, 2], -10, 10)) * aw
    bh = jnp.exp(jnp.clip(a[:, :, 3], -10, 10)) * ah
    return jnp.stack([bx, by, bw, bh], axis=-1)            # (N,na,H,W,4)


def _iou_grid_vs_gt(pred, gt, valid):
    """max IoU of each predicted cell box against any valid gt box."""
    px1 = pred[..., 0] - pred[..., 2] / 2
    py1 = pred[..., 1] - pred[..., 3] / 2
    px2 = pred[..., 0] + pred[..., 2] / 2
    py2 = pred[..., 1] + pred[..., 3] / 2
    gx1 = (gt[..., 0] - gt[..., 2] / 2)[:, None, None, None, :]
    gy1 = (gt[..., 1] - gt[..., 3] / 2)[:, None, None, None, :]
    gx2 = (gt[..., 0] + gt[..., 2] / 2)[:, None, None, None, :]
    gy2 = (gt[..., 1] + gt[..., 3] / 2)[:, None, None, None, :]
    ix = jnp.maximum(jnp.minimum(px2[..., None], gx2) -
                     jnp.maximum(px1[..., None], gx1), 0)
    iy = jnp.maximum(jnp.minimum(py2[..., None], gy2) -
                     jnp.maximum(py1[..., None], gy1), 0)
    inter = ix * iy
    area_p = (px2 - px1)[..., None] * (py2 - py1)[..., None]
    area_g = (gx2 - gx1) * (gy2 - gy1)
    iou = inter / (area_p + area_g - inter + 1e-9)
    iou = iou * valid[:, None, None, None, :]
    return jnp.max(iou, axis=-1)


# ---------------------------------------------------------------------------
# RoI pooling family
# ---------------------------------------------------------------------------
def _roi_batch_index(boxes_num, n_boxes, N):
    idx = np.zeros((n_boxes,), np.int32)
    start = 0
    for b, cnt in enumerate(np.asarray(boxes_num).reshape(-1)):
        idx[start:start + int(cnt)] = b
        start += int(cnt)
    return idx


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign with bilinear sampling (reference ``vision/ops.py:1145`` /
    ``roi_align_op``)."""
    x, boxes = to_tensor(x), to_tensor(boxes)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    n_boxes = int(boxes.shape[0])
    N = int(x.shape[0])
    bidx = jnp.asarray(_roi_batch_index(
        np.asarray(to_tensor(boxes_num)._data), n_boxes, N))
    if sampling_ratio <= 0:
        # reference uses ceil(roi_size/output) per RoI (dynamic shapes);
        # the static equivalent samples at the feature-map upper bound,
        # capped to keep cost bounded
        sr = min(8, max(2, -(-int(x.shape[2]) // int(oh))))
    else:
        sr = int(sampling_ratio)

    def impl(feat, bx):
        C, H, W = feat.shape[1:]
        off = 0.5 if aligned else 0.0
        x1 = bx[:, 0] * spatial_scale - off
        y1 = bx[:, 1] * spatial_scale - off
        x2 = bx[:, 2] * spatial_scale - off
        y2 = bx[:, 3] * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
        bin_w, bin_h = rw / ow, rh / oh
        # sample sr x sr points per bin, bilinear, then average
        iy = (jnp.arange(oh)[:, None] + (jnp.arange(sr) + 0.5)[None] / sr)
        ix = (jnp.arange(ow)[:, None] + (jnp.arange(sr) + 0.5)[None] / sr)
        ys = y1[:, None, None] + bin_h[:, None, None] * iy[None]  # (R,oh,sr)
        xs = x1[:, None, None] + bin_w[:, None, None] * ix[None]

        def sample(f, yy, xx):
            # f: (C, H, W); yy/xx: scalars -> bilinear value (C,)
            y0 = jnp.clip(jnp.floor(yy).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx).astype(jnp.int32), 0, W - 1)
            y1c = jnp.clip(y0 + 1, 0, H - 1)
            x1c = jnp.clip(x0 + 1, 0, W - 1)
            ly = jnp.clip(yy - y0, 0, 1)
            lx = jnp.clip(xx - x0, 0, 1)
            v = (f[:, y0, x0] * (1 - ly) * (1 - lx) +
                 f[:, y1c, x0] * ly * (1 - lx) +
                 f[:, y0, x1c] * (1 - ly) * lx +
                 f[:, y1c, x1c] * ly * lx)
            inside = (yy > -1) & (yy < H) & (xx > -1) & (xx < W)
            return v * inside

        def per_roi(b, ys_r, xs_r):
            f = feat[b]
            vals = jax.vmap(lambda yy: jax.vmap(
                lambda xx: jax.vmap(lambda y1s: jax.vmap(
                    lambda x1s: sample(f, y1s, x1s))(xx))(yy))(xs_r))(ys_r)
            # vals: (oh, ow, sr, sr, C) -> average samples
            return jnp.mean(vals, axis=(2, 3)).transpose(2, 0, 1)

        return jax.vmap(per_roi)(bidx, ys, xs)             # (R, C, oh, ow)
    return dispatch("roi_align", impl, (x, boxes), {})


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
             name=None):
    """RoIPool (max in each bin; reference ``vision/ops.py:1022``)."""
    x, boxes = to_tensor(x), to_tensor(boxes)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    n_boxes = int(boxes.shape[0])
    bidx = jnp.asarray(_roi_batch_index(
        np.asarray(to_tensor(boxes_num)._data), n_boxes, int(x.shape[0])))

    def impl(feat, bx):
        C, H, W = feat.shape[1:]
        x1 = jnp.round(bx[:, 0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(bx[:, 1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.maximum(jnp.round(bx[:, 2] * spatial_scale), x1 + 1)
        y2 = jnp.maximum(jnp.round(bx[:, 3] * spatial_scale), y1 + 1)

        ii = jnp.arange(H)[None, :]
        jj = jnp.arange(W)[None, :]

        def per_roi(b, xx1, yy1, xx2, yy2):
            f = feat[b]                                    # (C, H, W)
            rh = (yy2 - yy1).astype(jnp.float32) / oh
            rw = (xx2 - xx1).astype(jnp.float32) / ow
            outs = []
            for ph in range(oh):
                y_lo = yy1 + jnp.floor(ph * rh).astype(jnp.int32)
                y_hi = yy1 + jnp.ceil((ph + 1) * rh).astype(jnp.int32)
                row_mask = (ii[0][None, :] >= y_lo) & (ii[0][None, :] < y_hi)
                for pw in range(ow):
                    x_lo = xx1 + jnp.floor(pw * rw).astype(jnp.int32)
                    x_hi = xx1 + jnp.ceil((pw + 1) * rw).astype(jnp.int32)
                    col_mask = (jj[0][None, :] >= x_lo) & \
                        (jj[0][None, :] < x_hi)
                    m = row_mask.reshape(-1, 1) & col_mask.reshape(1, -1)
                    vals = jnp.where(m[None], f, -jnp.inf)
                    v = jnp.max(vals, axis=(1, 2))
                    # bins entirely outside the map output 0 (reference
                    # clips hstart/hend and zero-fills empty bins)
                    outs.append(jnp.where(jnp.isfinite(v), v, 0.0))
            return jnp.stack(outs, -1).reshape(C, oh, ow)
        return jax.vmap(per_roi)(bidx, x1, y1, x2, y2)
    return dispatch("roi_pool", impl, (x, boxes), {})


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (reference
    ``vision/ops.py:911``): channel c of output bin (i, j) reads input
    channel c*oh*ow + i*ow + j."""
    x, boxes = to_tensor(x), to_tensor(boxes)
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    C_in = int(x.shape[1])
    if C_in % (oh * ow) != 0:
        raise ValueError(
            f"psroi_pool needs channels ({C_in}) divisible by "
            f"output_size^2 ({oh * ow})")
    C_out = C_in // (oh * ow)
    bidx = jnp.asarray(_roi_batch_index(
        np.asarray(to_tensor(boxes_num)._data), int(boxes.shape[0]),
        int(x.shape[0])))

    def impl(feat, bx):
        H, W = feat.shape[2:]
        x1 = bx[:, 0] * spatial_scale
        y1 = bx[:, 1] * spatial_scale
        x2 = bx[:, 2] * spatial_scale
        y2 = bx[:, 3] * spatial_scale
        rh, rw = (y2 - y1) / oh, (x2 - x1) / ow
        ii = jnp.arange(H)
        jj = jnp.arange(W)

        def per_roi(b, xx1, yy1, hh, ww):
            f = feat[b].reshape(C_out, oh * ow, H, W)
            outs = []
            for ph in range(oh):
                y_lo, y_hi = yy1 + ph * hh, yy1 + (ph + 1) * hh
                rmask = (ii >= jnp.floor(y_lo)) & (ii < jnp.ceil(y_hi))
                for pw in range(ow):
                    x_lo, x_hi = xx1 + pw * ww, xx1 + (pw + 1) * ww
                    cmask = (jj >= jnp.floor(x_lo)) & (jj < jnp.ceil(x_hi))
                    m = (rmask[:, None] & cmask[None, :]).astype(f.dtype)
                    cnt = jnp.maximum(jnp.sum(m), 1.0)
                    chan = f[:, ph * ow + pw]              # (C_out, H, W)
                    outs.append(jnp.sum(chan * m[None], axis=(1, 2)) / cnt)
            return jnp.stack(outs, -1).reshape(C_out, oh, ow)
        return jax.vmap(per_roi)(bidx, x1, y1, rh, rw)
    return dispatch("psroi_pool", impl, (x, boxes), {})


# ---------------------------------------------------------------------------
# deformable conv
# ---------------------------------------------------------------------------
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference ``vision/ops.py:423`` /
    ``deformable_conv_op``): sampling positions are offset per output
    location, bilinear-gathered, then contracted with the weight (v2 when
    ``mask`` is given)."""
    x, offset, weight = to_tensor(x), to_tensor(offset), to_tensor(weight)
    tensors = [x, offset, weight]
    if mask is not None:
        tensors.append(to_tensor(mask))
    if bias is not None:
        tensors.append(to_tensor(bias))
    has_mask = mask is not None
    has_bias = bias is not None
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else \
        tuple(dilation)

    def impl(a, off, w, *rest):
        msk = rest[0] if has_mask else None
        b = rest[-1] if has_bias else None
        N, C, H, W = a.shape
        Co, Cg, kh, kw = w.shape
        Ho = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        Wo = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        K = kh * kw
        off = off.reshape(N, deformable_groups, K, 2, Ho, Wo)

        base_y = (jnp.arange(Ho) * s[0] - p[0])[:, None]
        base_x = (jnp.arange(Wo) * s[1] - p[1])[None, :]
        kyx = jnp.stack(jnp.meshgrid(jnp.arange(kh) * d[0],
                                     jnp.arange(kw) * d[1],
                                     indexing="ij"), -1).reshape(K, 2)

        cpg = C // deformable_groups   # channels per deformable group

        def sample_chan(f2d, yy, xx):
            y0 = jnp.floor(yy).astype(jnp.int32)
            x0 = jnp.floor(xx).astype(jnp.int32)
            ly, lx = yy - y0, xx - x0
            def g(yi, xi):
                inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
                v = f2d[jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
                return v * inside
            return (g(y0, x0) * (1 - ly) * (1 - lx) +
                    g(y0 + 1, x0) * ly * (1 - lx) +
                    g(y0, x0 + 1) * (1 - ly) * lx +
                    g(y0 + 1, x0 + 1) * ly * lx)

        def per_n(an, offn, mskn):
            # sampling grid per (dg, K, Ho, Wo)
            yy = base_y[None, None] + kyx[None, :, 0][..., None, None] + \
                offn[:, :, 0]
            xx = base_x[None, None] + kyx[None, :, 1][..., None, None] + \
                offn[:, :, 1]
            # gather per channel: (C, K, Ho, Wo)
            def per_c(c):
                dg = c // cpg
                return jax.vmap(lambda k: sample_chan(
                    an[c], yy[dg, k], xx[dg, k]))(jnp.arange(K))
            cols = jax.vmap(per_c)(jnp.arange(C))
            if mskn is not None:
                m = mskn.reshape(deformable_groups, K, Ho, Wo)
                m = jnp.repeat(m, cpg, axis=0)             # (C, K, Ho, Wo)
                cols = cols * m
            return cols

        if msk is None:
            cols = jax.vmap(per_n, in_axes=(0, 0, None))(a, off, None)
        else:
            cols = jax.vmap(per_n)(a, off, msk)
        # contraction: out[n, co, ho, wo] = sum_{cg, k} w * cols
        wg = w.reshape(groups, Co // groups, Cg, K)
        colsg = cols.reshape(N, groups, Cg, K, Ho, Wo)
        out = jnp.einsum("gock,ngckhw->ngohw", wg, colsg)
        out = out.reshape(N, Co, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, Co, 1, 1)
        return out
    return dispatch("deform_conv2d", impl, tensors, {})


class DeformConv2D(Layer):
    """Layer wrapper (reference ``vision/ops.py:626``)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        kh, kw = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self._cfg = dict(stride=stride, padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups, groups=groups)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw], attr=weight_attr)
        self.bias = None if bias_attr is False else self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._cfg)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


# ---------------------------------------------------------------------------
# detection family re-exports + host image IO (reference
# paddle/vision/ops.py nms; operators/detection/*; read_file /
# decode_jpeg ops run host-side CPU kernels in the reference too)
# ---------------------------------------------------------------------------
from .detection import (  # noqa: E402,F401
    nms, multiclass_nms, matrix_nms, distribute_fpn_proposals,
    generate_proposals, prior_box, box_coder,
)

__all__ += ["nms", "multiclass_nms", "matrix_nms",
            "distribute_fpn_proposals", "generate_proposals",
            "prior_box", "box_coder", "read_file", "decode_jpeg"]


def read_file(path, name=None):
    """Read raw file bytes as a uint8 tensor (reference
    operators/read_file_op.cc — a host-side kernel there as well)."""
    with open(path, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference
    operators/decode_jpeg_op.cu uses nvjpeg; host PIL decode is the TPU
    translation — image decode feeds the input pipeline, not the MXU)."""
    import io as _io
    from PIL import Image as _Image
    data = bytes(bytearray(np.asarray(
        x._data if isinstance(x, Tensor) else x, dtype=np.uint8)))
    img = _Image.open(_io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]                      # [1, H, W]
    else:
        arr = arr.transpose(2, 0, 1)         # [C, H, W]
    return Tensor(jnp.asarray(arr))

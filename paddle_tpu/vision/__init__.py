"""paddle.vision namespace (reference python/paddle/vision/__init__.py
re-exports models / transforms / datasets at the package level)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .models import *  # noqa: F401,F403
from .transforms import *  # noqa: F401,F403
from .datasets import *  # noqa: F401,F403

_image_backend = "numpy"


def set_image_backend(backend):
    """reference vision/image.py set_image_backend (pil/cv2/numpy; only
    the numpy/tensor path exists in this zero-dependency build)."""
    global _image_backend
    if backend not in ("pil", "cv2", "numpy", "tensor"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file as an HWC uint8 array (reference image_load);
    npy/npz natively, PIL only if available."""
    import numpy as np
    if str(path).endswith(".npy"):
        return np.load(path)
    if str(path).endswith(".npz"):
        z = np.load(path)
        return z[z.files[0]]
    try:
        from PIL import Image
        return np.asarray(Image.open(path))
    except ImportError as e:
        raise RuntimeError(
            "image decoding needs PIL, which is not available; save "
            "arrays as .npy or decode in your own loader") from e


from . import detection  # noqa: E402,F401

"""ASP — automatic structured (n:m) sparsity (``paddle.static.sparsity``
/ fleet ASP parity).

Reference parity: ``python/paddle/fluid/contrib/sparsity/`` —
``utils.py:87`` calculate_density, ``:137`` check_mask_1d, ``:181``
get_mask_1d, ``:264/:314/:422`` 2d variants, ``:475`` create_mask,
``:537`` check_sparsity; ``asp.py:110`` decorate, ``:149`` prune_model,
``:31/:72`` excluded-layer registry.

TPU-first: masks are jnp arrays applied as a pure elementwise multiply
that XLA fuses into the weight's consumer matmul; the ASP-decorated
optimizer re-applies masks after each step (the reference appends masking
ops to the program — here it's a post-step hook in eager mode and a
mask-multiply folded into the jitted update in functional mode).
2:4 weights feed the MXU densely today; the mask discipline keeps models
convertible to sparse acceleration when available.
"""
from __future__ import annotations

import itertools
from enum import Enum
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["MaskAlgo", "CheckMethod", "calculate_density", "check_mask_1d",
           "get_mask_1d", "check_mask_2d", "get_mask_2d_greedy",
           "get_mask_2d_best", "create_mask", "check_sparsity",
           "set_excluded_layers", "reset_excluded_layers", "decorate",
           "prune_model", "ASPHelper"]


class MaskAlgo(Enum):
    MASK_1D = "get_mask_1d"
    MASK_2D_GREEDY = "get_mask_2d_greedy"
    MASK_2D_BEST = "get_mask_2d_best"


class CheckMethod(Enum):
    CHECK_1D = "check_mask_1d"
    CHECK_2D = "check_mask_2d"

    @staticmethod
    def get_checking_method(mask_algo: MaskAlgo):
        return CheckMethod.CHECK_1D if mask_algo == MaskAlgo.MASK_1D \
            else CheckMethod.CHECK_2D


def calculate_density(x) -> float:
    """Fraction of nonzeros (reference ``utils.py:87``)."""
    a = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(a)) / a.size


def _reshape_1d(mat: np.ndarray, m: int):
    pad = (m - mat.shape[1] % m) % m
    padded = np.concatenate(
        [mat, np.zeros((mat.shape[0], pad), mat.dtype)], axis=1)
    return padded.reshape(-1, m), padded.shape


def check_mask_1d(mat, n: int, m: int) -> bool:
    """Every m-wide row chunk has >= n zeros (reference ``utils.py:137``)."""
    mat = np.asarray(mat)
    groups, _ = _reshape_1d(mat, m)
    return bool(((groups == 0).sum(axis=1) >= n).all())


def get_mask_1d(mat, n: int, m: int) -> np.ndarray:
    """Keep the (m-n) largest |values| of each m-chunk
    (reference ``utils.py:181``)."""
    mat = np.asarray(mat)
    groups, padded_shape = _reshape_1d(mat, m)
    keep = m - n
    order = np.argsort(np.abs(groups), axis=1)
    mask = np.zeros_like(groups)
    rows = np.arange(groups.shape[0])[:, None]
    mask[rows, order[:, -keep:]] = 1.0
    mask = mask.reshape(padded_shape)[:, : mat.shape[1]]
    return mask.astype(mat.dtype)


def _reshape_2d(mat: np.ndarray, m: int):
    pad_r = (m - mat.shape[0] % m) % m
    pad_c = (m - mat.shape[1] % m) % m
    padded = np.pad(mat, ((0, pad_r), (0, pad_c)))
    H, W = padded.shape
    blocks = padded.reshape(H // m, m, W // m, m).transpose(0, 2, 1, 3)
    return blocks.reshape(-1, m, m), padded.shape


def check_mask_2d(mat, n: int, m: int) -> bool:
    """Every m x m block is n:m sparse along BOTH axes
    (reference ``utils.py:264``)."""
    mat = np.asarray(mat)
    blocks, _ = _reshape_2d(mat, m)
    nz = blocks != 0
    return bool((nz.sum(axis=1) <= m - n).all() and
                (nz.sum(axis=2) <= m - n).all())


def get_mask_2d_greedy(mat, n: int, m: int) -> np.ndarray:
    """Greedy 2d mask: pick largest entries under per-row/col budgets
    (reference ``utils.py:314``)."""
    mat = np.asarray(mat)
    blocks, padded_shape = _reshape_2d(mat, m)
    keep = m - n
    masks = np.zeros_like(blocks)
    for b in range(blocks.shape[0]):
        absb = np.abs(blocks[b])
        order = np.dstack(np.unravel_index(
            np.argsort(-absb, axis=None), (m, m)))[0]
        row_cnt = np.zeros(m, np.int64)
        col_cnt = np.zeros(m, np.int64)
        for r, c in order:
            if row_cnt[r] < keep and col_cnt[c] < keep:
                masks[b, r, c] = 1.0
                row_cnt[r] += 1
                col_cnt[c] += 1
    return _blocks_to_mat(masks, padded_shape, mat, m)


_PATTERNS_CACHE: Dict[tuple, np.ndarray] = {}


def _valid_2d_patterns(n: int, m: int) -> np.ndarray:
    """All m x m 0/1 patterns with exactly (m-n) ones per row and per
    column (reference ``utils.py:384``)."""
    key = (n, m)
    if key in _PATTERNS_CACHE:
        return _PATTERNS_CACHE[key]
    keep = m - n
    rows = [np.array(p) for p in itertools.product([0, 1], repeat=m)
            if sum(p) == keep]
    pats = []
    for combo in itertools.product(range(len(rows)), repeat=m):
        pat = np.stack([rows[i] for i in combo])
        if (pat.sum(axis=0) == keep).all():
            pats.append(pat)
    out = np.stack(pats).astype(np.float64)
    _PATTERNS_CACHE[key] = out
    return out


def get_mask_2d_best(mat, n: int, m: int) -> np.ndarray:
    """Exhaustive best 2d pattern per block (reference ``utils.py:422``)."""
    mat = np.asarray(mat)
    blocks, padded_shape = _reshape_2d(mat, m)
    pats = _valid_2d_patterns(n, m)
    scores = np.einsum("bij,pij->bp", np.abs(blocks.astype(np.float64)),
                       pats)
    best = pats[np.argmax(scores, axis=1)]
    return _blocks_to_mat(best.astype(mat.dtype), padded_shape, mat, m)


def _blocks_to_mat(blocks, padded_shape, mat, m):
    H, W = padded_shape
    out = blocks.reshape(H // m, W // m, m, m).transpose(0, 2, 1, 3)
    out = out.reshape(H, W)[: mat.shape[0], : mat.shape[1]]
    return out.astype(mat.dtype)


def create_mask(tensor, func_name=MaskAlgo.MASK_1D, n=2, m=4) -> np.ndarray:
    """n:m mask for a 2D-or-higher weight (reference ``utils.py:475``);
    >2D tensors are masked over their trailing-2D reshape."""
    t = np.asarray(tensor._data if isinstance(tensor, Tensor) else tensor)
    shape = t.shape
    if t.ndim < 2:
        raise ValueError("ASP masks need >= 2D weights")
    mat = t.reshape(shape[0], -1)
    fn = globals()[MaskAlgo(func_name).value] if not isinstance(
        func_name, MaskAlgo) else globals()[func_name.value]
    mask = fn(mat, n, m)
    return mask.reshape(shape)


def check_sparsity(tensor, func_name=CheckMethod.CHECK_1D, n=2, m=4) -> bool:
    t = np.asarray(tensor._data if isinstance(tensor, Tensor) else tensor)
    mat = t.reshape(t.shape[0], -1)
    fn = globals()[CheckMethod(func_name).value] if not isinstance(
        func_name, CheckMethod) else globals()[func_name.value]
    return fn(mat, n, m)


# ---------------------------------------------------------------------------
# model-level ASP (reference asp.py)
# ---------------------------------------------------------------------------
class ASPHelper:
    """Tracks per-model masks (reference ``asp.py:275``)."""

    _excluded: set = set()
    MIN_DIM = 32  # reference skips small layers

    @classmethod
    def supported(cls, name: str, param) -> bool:
        if name in cls._excluded:
            return False
        shape = tuple(param.shape)
        if len(shape) < 2:
            return False
        if shape[0] < cls.MIN_DIM or int(np.prod(shape[1:])) < cls.MIN_DIM:
            return False
        # convention: weights only (biases/norm scales are 1D anyway)
        return True


def set_excluded_layers(param_names: List[str], main_program=None):
    """reference ``asp.py:31`` (program arg kept for signature parity)."""
    ASPHelper._excluded |= set(param_names)


def reset_excluded_layers(main_program=None):
    ASPHelper._excluded = set()


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Apply n:m masks to every supported weight of an nn.Layer
    (reference ``asp.py:149`` prune_model on a Program).  Returns the
    {param_name: mask} dict used later by the decorated optimizer."""
    algo = {"mask_1d": MaskAlgo.MASK_1D,
            "mask_2d_greedy": MaskAlgo.MASK_2D_GREEDY,
            "mask_2d_best": MaskAlgo.MASK_2D_BEST}[mask_algo]
    masks: Dict[int, jnp.ndarray] = {}
    for name, p in model.named_parameters():
        if not ASPHelper.supported(name, p):
            continue
        mask = jnp.asarray(create_mask(p, algo, n, m))
        p._data = p._data * mask.astype(p._data.dtype)
        if with_mask:
            masks[id(p)] = mask
    return masks


class OptimizerWithSparsityGuarantee:
    """Re-applies masks after every step (reference ``asp.py:535``).

    A parameter registered with mask ``None`` is still dense (reference
    call order decorate -> prune_model): its mask is captured lazily at
    the first step after pruning zeroes it."""

    def __init__(self, optimizer, masks: Dict[int, jnp.ndarray]):
        self._opt = optimizer
        self._masks = masks

    def __getattr__(self, item):
        return getattr(self._opt, item)

    def _lazy_capture(self):
        for p in self._opt._parameter_list or []:
            key = id(p)
            if key in self._masks and self._masks[key] is None:
                pattern = (np.asarray(p._data) != 0).astype(np.float32)
                if not pattern.all():  # pruned since decorate()
                    self._masks[key] = jnp.asarray(pattern)

    def step(self):
        self._lazy_capture()
        self._opt.step()
        for p in self._opt._parameter_list or []:
            mask = self._masks.get(id(p))
            if mask is not None:
                p._data = p._data * mask.astype(p._data.dtype)

    def minimize(self, loss, *args, **kwargs):
        out = self._opt.minimize(loss, *args, **kwargs)
        self.step_masks_only()
        return out

    def step_masks_only(self):
        for p in self._opt._parameter_list or []:
            mask = self._masks.get(id(p))
            if mask is not None:
                p._data = p._data * mask.astype(p._data.dtype)


def decorate(optimizer, masks: Optional[Dict[int, jnp.ndarray]] = None):
    """Wrap an optimizer so updates preserve the pruned pattern
    (reference ``asp.py:110``).  Without explicit masks the weights must
    already be pruned — snapshotting a dense weight's nonzero pattern
    would make the guarantee an all-ones no-op, so that case errors."""
    if masks is None:
        masks = {}
        for p in optimizer._parameter_list or []:
            if len(p.shape) >= 2 and ASPHelper.supported(p.name or "", p):
                pattern = (np.asarray(p._data) != 0).astype(np.float32)
                # still-dense weight: reference order is decorate ->
                # prune_model, so register for lazy capture at the first
                # post-pruning step rather than snapshotting all-ones
                masks[id(p)] = None if pattern.all() \
                    else jnp.asarray(pattern)
    return OptimizerWithSparsityGuarantee(optimizer, masks)

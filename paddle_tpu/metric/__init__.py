"""paddle.metric (reference python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor, to_tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional device-side pre-reduction before update()."""
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = np.zeros(len(self.topk))
        self.count = np.zeros(len(self.topk))

    def compute(self, pred, label, *args):
        pred = to_tensor(pred)
        label = to_tensor(label)
        import jax.numpy as jnp
        import jax
        _, idx = jax.lax.top_k(pred._data, self.maxk)
        lbl = label._data
        if lbl.ndim == idx.ndim:
            lbl = jnp.squeeze(lbl, -1) if lbl.shape[-1] == 1 else \
                jnp.argmax(lbl, -1)
        correct = (idx == lbl[..., None])
        return Tensor(correct)

    def update(self, correct, *args):
        c = np.asarray(to_tensor(correct)._data)
        num = c.shape[0] if c.ndim > 1 else len(c)
        accs = []
        for i, k in enumerate(self.topk):
            hit = c[..., :k].any(axis=-1).sum()
            self.total[i] += hit
            self.count[i] += c.reshape(-1, c.shape[-1]).shape[0]
            accs.append(float(hit) / max(c.reshape(-1, c.shape[-1]).shape[0], 1))
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        out = [t / max(c, 1e-12) for t, c in zip(self.total, self.count)]
        return out[0] if len(out) == 1 else out

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(to_tensor(preds)._data).reshape(-1)
        l = np.asarray(to_tensor(labels)._data).reshape(-1)
        pred_pos = (p > 0.5).astype(np.int64)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fp += int(((pred_pos == 1) & (l == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(to_tensor(preds)._data).reshape(-1)
        l = np.asarray(to_tensor(labels)._data).reshape(-1)
        pred_pos = (p > 0.5).astype(np.int64)
        self.tp += int(((pred_pos == 1) & (l == 1)).sum())
        self.fn += int(((pred_pos == 0) & (l == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args,
                 **kwargs):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(to_tensor(preds)._data)
        l = np.asarray(to_tensor(labels)._data).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        else:
            p = p.reshape(-1)
        bins = np.round(p * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference operators/metrics/accuracy_op)."""
    import jax
    import jax.numpy as jnp
    input, label = to_tensor(input), to_tensor(label)
    _, idx = jax.lax.top_k(input._data, k)
    lbl = label._data
    if lbl.ndim == idx.ndim and lbl.shape[-1] == 1:
        lbl = jnp.squeeze(lbl, -1)
    hit = (idx == lbl[..., None]).any(axis=-1)
    return Tensor(jnp.mean(hit.astype(jnp.float32)))

"""Probability distributions (``paddle.distribution`` parity).

Reference parity: ``python/paddle/distribution.py`` — ``Distribution``
(:42), ``Uniform`` (:169), ``Normal`` (:391), ``Categorical`` (:641) with
sample / entropy / log_prob / probs / kl_divergence.

TPU-first design: every density computation goes through the dispatched op
surface so eager autograd records it (reparameterised sampling is therefore
differentiable w.r.t. the distribution parameters), and all draws use the
counter-based JAX PRNG via the framework Generator — inside ``jit`` the
functional rng_scope supplies keys, so sampling is trace-safe.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import ops as P
from .core.random import default_generator
from .core.tensor import Tensor, to_tensor

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _f32(x):
    """Coerce python scalars / lists / Tensors to a float Tensor."""
    t = to_tensor(x)
    if not jnp.issubdtype(t.dtype, jnp.floating):
        t = t.astype("float32")
    return t


def _ext_shape(shape, batch_shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if shape is None:
        shape = ()
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    return tuple(int(s) for s in shape) + tuple(batch_shape)


class Distribution:
    """Abstract base class for probability distributions
    (reference ``distribution.py:42``)."""

    def sample(self, shape=()):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """Uniform distribution on [low, high) (reference ``distribution.py:169``).

    pdf(x; a, b) = 1 / (b - a) for a <= x < b.
    """

    def __init__(self, low, high, name=None):
        self.low = _f32(low)
        self.high = _f32(high)
        self.name = name or "Uniform"
        self._batch_shape = jnp.broadcast_shapes(
            tuple(self.low.shape), tuple(self.high.shape))

    def sample(self, shape=(), seed=0):
        out_shape = _ext_shape(shape, self._batch_shape)
        key = (jax.random.PRNGKey(seed) if seed else
               default_generator.next_key())
        u = Tensor(jax.random.uniform(key, out_shape, jnp.float32))
        # reparameterised: low + u * (high - low) — differentiable in params
        return P.add(self.low, P.multiply(u, P.subtract(self.high, self.low)))

    def log_prob(self, value):
        value = _f32(value)
        lb = P.cast(P.less_equal(self.low, value), value.dtype)
        ub = P.cast(P.less_than(value, self.high), value.dtype)
        return P.subtract(P.log(P.multiply(lb, ub)),
                          P.log(P.subtract(self.high, self.low)))

    def probs(self, value):
        value = _f32(value)
        lb = P.cast(P.less_equal(self.low, value), value.dtype)
        ub = P.cast(P.less_than(value, self.high), value.dtype)
        return P.divide(P.multiply(lb, ub),
                        P.subtract(self.high, self.low))

    def entropy(self):
        return P.log(P.subtract(self.high, self.low))


class Normal(Distribution):
    """Normal (Gaussian) distribution (reference ``distribution.py:391``)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _f32(loc)
        self.scale = _f32(scale)
        self.name = name or "Normal"
        self._batch_shape = jnp.broadcast_shapes(
            tuple(self.loc.shape), tuple(self.scale.shape))

    def sample(self, shape=(), seed=0):
        out_shape = _ext_shape(shape, self._batch_shape)
        key = (jax.random.PRNGKey(seed) if seed else
               default_generator.next_key())
        eps = Tensor(jax.random.normal(key, out_shape, jnp.float32))
        # reparameterised: loc + eps * scale
        return P.add(self.loc, P.multiply(eps, self.scale))

    def entropy(self):
        # 0.5 + 0.5*log(2*pi) + log(sigma), broadcast over batch shape
        half_log_2pi = 0.5 * float(np.log(2.0 * np.pi))
        zeros = P.zeros(self._batch_shape, "float32")
        return P.add(P.add(P.full([], 0.5 + half_log_2pi, "float32"),
                           P.log(self.scale)), zeros)

    def log_prob(self, value):
        value = _f32(value)
        var = P.multiply(self.scale, self.scale)
        diff = P.subtract(value, self.loc)
        return P.subtract(
            P.divide(P.multiply(diff, diff), P.scale(var, -2.0)),
            P.add(P.log(self.scale),
                  P.full([], 0.5 * float(np.log(2.0 * np.pi)), "float32")))

    def probs(self, value):
        return P.exp(self.log_prob(value))

    def kl_divergence(self, other):
        """KL(self || other) for two Normals (reference ``distribution.py:596``)."""
        if not isinstance(other, Normal):
            raise TypeError("kl_divergence expects a Normal")
        var_ratio = P.divide(self.scale, other.scale)
        var_ratio = P.multiply(var_ratio, var_ratio)
        t1 = P.divide(P.subtract(self.loc, other.loc), other.scale)
        t1 = P.multiply(t1, t1)
        return P.scale(
            P.subtract(P.add(var_ratio, t1),
                       P.add(P.full([], 1.0, "float32"), P.log(var_ratio))),
            0.5)


class Categorical(Distribution):
    """Categorical over the last axis of ``logits``
    (reference ``distribution.py:641``)."""

    def __init__(self, logits, name=None):
        self.logits = _f32(logits)
        self.name = name or "Categorical"
        self._num_events = int(self.logits.shape[-1])

    def _norm_probs(self):
        return P.softmax(self.logits, axis=-1)

    def sample(self, shape=()):
        if isinstance(shape, Tensor):
            shape = shape.tolist()
        if isinstance(shape, (int, np.integer)):
            shape = (int(shape),)
        shape = tuple(int(s) for s in shape)
        key = default_generator.next_key()
        logits = jnp.asarray(self.logits._data)
        batch = logits.shape[:-1]
        draw = jax.random.categorical(
            key, logits, axis=-1, shape=shape + batch)
        return Tensor(draw.astype(jnp.int64))

    def entropy(self):
        p = self._norm_probs()
        logp = P.log_softmax(self.logits, axis=-1)
        return P.scale(P.sum(P.multiply(p, logp), axis=-1), -1.0)

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise TypeError("kl_divergence expects a Categorical")
        logp = P.log_softmax(self.logits, axis=-1)
        logq = P.log_softmax(other.logits, axis=-1)
        p = self._norm_probs()
        return P.sum(P.multiply(p, P.subtract(logp, logq)), axis=-1)

    def probs(self, value):
        """Probabilities of the chosen category indices ``value``
        (reference ``distribution.py:864``)."""
        p = self._norm_probs()
        value = to_tensor(value).astype("int64")
        if p.ndim == 1:
            return P.gather(p, value)
        got = P.take_along_axis(p, P.unsqueeze(value, axis=-1), axis=-1)
        return P.squeeze(got, axis=-1)

    def log_prob(self, value):
        return P.log(self.probs(value))

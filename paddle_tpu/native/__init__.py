"""Native (C++) runtime support: blocking queue, arena allocator,
profiler events, stat registry.

Reference parity map (see src/native.cc header): blocking_queue.h,
auto_growth_best_fit_allocator.h:30, platform/profiler.h:216,
platform/monitor.h:77.

The library is compiled in-repo on first use (g++ -O2 -shared) and bound
via ctypes — the image has no pybind11, and a C ABI keeps the binding
layer trivial.  Every consumer has a pure-Python fallback so the
framework still works if no toolchain is present.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

__all__ = ["available", "lib", "BlockingQueue", "Arena", "Profiler",
           "stat_add", "stat_get", "stat_reset"]

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "native.cc")
_SO = os.path.join(_HERE, "_paddle_native.so")

_lib = None
_lock = threading.Lock()


def _build() -> bool:
    try:
        src_mtime = os.path.getmtime(_SRC)
        if os.path.exists(_SO) and os.path.getmtime(_SO) >= src_mtime:
            return True
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               _SRC, "-o", _SO + ".tmp"]
        subprocess.run(cmd, check=True, capture_output=True, timeout=240)
        os.replace(_SO + ".tmp", _SO)
        return True
    except Exception:
        return False


def lib():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib if _lib is not False else None
    with _lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        if not _build():
            _lib = False
            return None
        try:
            L = ctypes.CDLL(_SO)
        except OSError:
            _lib = False
            return None
        # signatures
        L.arena_create.restype = ctypes.c_void_p
        L.arena_create.argtypes = [ctypes.c_uint64]
        L.arena_alloc.restype = ctypes.c_void_p
        L.arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        L.arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        L.arena_reserved.restype = ctypes.c_uint64
        L.arena_reserved.argtypes = [ctypes.c_void_p]
        L.arena_in_use.restype = ctypes.c_uint64
        L.arena_in_use.argtypes = [ctypes.c_void_p]
        L.arena_destroy.argtypes = [ctypes.c_void_p]
        L.bq_create.restype = ctypes.c_void_p
        L.bq_create.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
        L.bq_push.restype = ctypes.c_int
        L.bq_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.c_uint64, ctypes.c_int64]
        L.bq_peek_size.restype = ctypes.c_int64
        L.bq_peek_size.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        L.bq_fetch.restype = ctypes.c_int64
        L.bq_fetch.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                               ctypes.c_uint64]
        L.bq_size.restype = ctypes.c_uint64
        L.bq_size.argtypes = [ctypes.c_void_p]
        L.bq_close.argtypes = [ctypes.c_void_p]
        L.bq_destroy.argtypes = [ctypes.c_void_p]
        L.prof_enable.argtypes = [ctypes.c_uint64]
        L.prof_is_enabled.restype = ctypes.c_int
        L.prof_now_ns.restype = ctypes.c_int64
        L.prof_record.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_int64, ctypes.c_int64]
        L.prof_event_count.restype = ctypes.c_uint64
        L.prof_dump_json.restype = ctypes.c_int64
        L.prof_dump_json.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        L.stat_add.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        L.stat_get.restype = ctypes.c_int64
        L.stat_get.argtypes = [ctypes.c_char_p]
        L.stat_reset.argtypes = [ctypes.c_char_p]
        _lib = L
    return _lib


def available() -> bool:
    return lib() is not None


class Arena:
    """Host staging-buffer allocator (auto-growth best-fit)."""

    def __init__(self, chunk_size: int = 8 << 20):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._L = L
        self._h = L.arena_create(chunk_size)

    def alloc(self, size: int) -> int:
        return self._L.arena_alloc(self._h, size)

    def free(self, ptr: int):
        self._L.arena_free(self._h, ctypes.c_void_p(ptr))

    @property
    def reserved(self) -> int:
        return self._L.arena_reserved(self._h)

    @property
    def in_use(self) -> int:
        return self._L.arena_in_use(self._h)

    def __del__(self):
        try:
            self._L.arena_destroy(self._h)
        except Exception:
            pass


class BlockingQueue:
    """Bounded byte-buffer queue; blocking waits run outside the GIL
    (ctypes releases it), so producer/consumer threads overlap with
    device compute — reference blocking_queue.h semantics."""

    def __init__(self, capacity: int = 8, arena_chunk: int = 8 << 20):
        L = lib()
        if L is None:
            raise RuntimeError("native library unavailable")
        self._L = L
        self._h = L.bq_create(capacity, arena_chunk)

    def push(self, data: bytes, timeout_ms: int = -1) -> bool:
        rc = self._L.bq_push(self._h, data, len(data), timeout_ms)
        if rc == -1:
            raise RuntimeError("queue closed")
        if rc == -3:
            raise MemoryError("arena alloc failed")
        return rc == 0

    def pop(self, timeout_ms: int = -1):
        size = self._L.bq_peek_size(self._h, timeout_ms)
        if size == -1:
            return None  # closed + drained
        if size == -2:
            raise TimeoutError("queue pop timed out")
        buf = ctypes.create_string_buffer(int(size))
        got = self._L.bq_fetch(self._h, buf, int(size))
        if got < 0:
            return None
        return buf.raw[:got]

    def __len__(self):
        return int(self._L.bq_size(self._h))

    def close(self):
        self._L.bq_close(self._h)

    def __del__(self):
        try:
            self._L.bq_destroy(self._h)
        except Exception:
            pass


class Profiler:
    """Host-span collector; chrome-trace export (profiler.h:216)."""

    @staticmethod
    def enable(capacity: int = 1 << 20):
        L = lib()
        if L is not None:
            L.prof_enable(capacity)

    @staticmethod
    def disable():
        L = lib()
        if L is not None:
            L.prof_disable()

    @staticmethod
    def enabled() -> bool:
        L = lib()
        return bool(L and L.prof_is_enabled())

    @staticmethod
    def now_ns() -> int:
        L = lib()
        return L.prof_now_ns() if L else 0

    @staticmethod
    def record(name: str, start_ns: int, end_ns: int, tid: int = 0):
        L = lib()
        if L is not None:
            L.prof_record(name.encode(), start_ns, end_ns, tid)

    @staticmethod
    def event_count() -> int:
        L = lib()
        return int(L.prof_event_count()) if L else 0

    @staticmethod
    def dump_chrome_trace(path: str):
        L = lib()
        if L is None:
            return
        cap = 1 << 20
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = L.prof_dump_json(buf, cap)
            if n >= 0:
                with open(path, "wb") as f:
                    f.write(buf.raw[:n])
                return
            cap = -int(n) + 16


def stat_add(name: str, delta: int = 1):
    L = lib()
    if L is not None:
        L.stat_add(name.encode(), delta)


def stat_get(name: str) -> int:
    L = lib()
    return int(L.stat_get(name.encode())) if L else 0


def stat_reset(name: str = ""):
    L = lib()
    if L is not None:
        L.stat_reset(name.encode())

// paddle_tpu native runtime support library.
//
// TPU-native equivalents of the reference's C++ host-side subsystems:
//  - BlockingQueue  <- paddle/fluid/operators/reader/blocking_queue.h
//      bounded MPMC byte-buffer queue; waits happen outside the Python
//      GIL (callers use ctypes, which releases the GIL for the call).
//  - Arena          <- paddle/fluid/memory/allocation/
//                      auto_growth_best_fit_allocator.h:30
//      chunked auto-growth best-fit allocator for host staging buffers
//      (DataLoader batches, checkpoint I/O) — avoids malloc churn and
//      keeps buffers alignment-friendly for zero-copy numpy views.
//  - Profiler       <- paddle/fluid/platform/profiler.h:216 (RecordEvent
//      + chrome-trace export); device-side tracing stays with XLA/jax
//      profiler, this records host spans.
//  - StatRegistry   <- paddle/fluid/platform/monitor.h:77
//      named int64 counters.
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Arena: chunked auto-growth best-fit allocator
// ---------------------------------------------------------------------------

struct FreeBlock {
  size_t size;
  char* ptr;
};

struct Arena {
  std::mutex mu;
  size_t chunk_size;
  size_t total_reserved = 0;
  size_t total_in_use = 0;
  std::vector<char*> chunks;
  // best-fit free list ordered by size (multimap: size -> ptr)
  std::multimap<size_t, char*> free_blocks;
  std::unordered_map<char*, size_t> sizes;  // live allocation sizes
};

static const size_t kAlign = 256;  // page/DMA friendly

static size_t align_up(size_t n) { return (n + kAlign - 1) & ~(kAlign - 1); }

void* arena_create(uint64_t chunk_size) {
  Arena* a = new Arena();
  a->chunk_size = chunk_size ? chunk_size : (8u << 20);
  return a;
}

void* arena_alloc(void* handle, uint64_t size) {
  Arena* a = static_cast<Arena*>(handle);
  size_t need = align_up(size);
  std::lock_guard<std::mutex> lock(a->mu);
  // best fit: smallest free block that holds `need`
  auto it = a->free_blocks.lower_bound(need);
  if (it == a->free_blocks.end()) {
    size_t chunk = std::max(a->chunk_size, need);
    char* mem = static_cast<char*>(::operator new(chunk, std::nothrow));
    if (!mem) return nullptr;
    a->chunks.push_back(mem);
    a->total_reserved += chunk;
    it = a->free_blocks.emplace(chunk, mem);
  }
  size_t bsize = it->first;
  char* bptr = it->second;
  a->free_blocks.erase(it);
  if (bsize > need + kAlign) {  // split the remainder back
    a->free_blocks.emplace(bsize - need, bptr + need);
    bsize = need;
  }
  a->sizes[bptr] = bsize;
  a->total_in_use += bsize;
  return bptr;
}

void arena_free(void* handle, void* ptr) {
  Arena* a = static_cast<Arena*>(handle);
  std::lock_guard<std::mutex> lock(a->mu);
  auto it = a->sizes.find(static_cast<char*>(ptr));
  if (it == a->sizes.end()) return;
  a->total_in_use -= it->second;
  a->free_blocks.emplace(it->second, it->first);
  a->sizes.erase(it);
}

uint64_t arena_reserved(void* handle) {
  return static_cast<Arena*>(handle)->total_reserved;
}

uint64_t arena_in_use(void* handle) {
  return static_cast<Arena*>(handle)->total_in_use;
}

void arena_destroy(void* handle) {
  Arena* a = static_cast<Arena*>(handle);
  for (char* c : a->chunks) ::operator delete(c);
  delete a;
}

// ---------------------------------------------------------------------------
// BlockingQueue of byte buffers (arena-backed)
// ---------------------------------------------------------------------------

struct Buf {
  char* ptr;
  size_t size;
};

struct BlockingQueue {
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::deque<Buf> items;
  size_t capacity;
  bool closed = false;
  Arena* arena;
};

void* bq_create(uint64_t capacity, uint64_t arena_chunk) {
  BlockingQueue* q = new BlockingQueue();
  q->capacity = capacity ? capacity : 8;
  q->arena = static_cast<Arena*>(arena_create(arena_chunk));
  return q;
}

// returns 0 ok, -1 closed, -2 timeout
int bq_push(void* handle, const void* data, uint64_t size,
            int64_t timeout_ms) {
  BlockingQueue* q = static_cast<BlockingQueue*>(handle);
  std::unique_lock<std::mutex> lock(q->mu);
  auto pred = [q] { return q->closed || q->items.size() < q->capacity; };
  if (timeout_ms < 0) {
    q->not_full.wait(lock, pred);
  } else if (!q->not_full.wait_for(
                 lock, std::chrono::milliseconds(timeout_ms), pred)) {
    return -2;
  }
  if (q->closed) return -1;
  char* mem = static_cast<char*>(arena_alloc(q->arena, size));
  if (!mem) return -3;
  std::memcpy(mem, data, size);
  q->items.push_back(Buf{mem, static_cast<size_t>(size)});
  q->not_empty.notify_one();
  return 0;
}

// returns size >=0, -1 closed+drained, -2 timeout. Caller then calls
// bq_fetch to copy out and release.
int64_t bq_peek_size(void* handle, int64_t timeout_ms) {
  BlockingQueue* q = static_cast<BlockingQueue*>(handle);
  std::unique_lock<std::mutex> lock(q->mu);
  auto pred = [q] { return q->closed || !q->items.empty(); };
  if (timeout_ms < 0) {
    q->not_empty.wait(lock, pred);
  } else if (!q->not_empty.wait_for(
                 lock, std::chrono::milliseconds(timeout_ms), pred)) {
    return -2;
  }
  if (q->items.empty()) return -1;  // closed + drained
  return static_cast<int64_t>(q->items.front().size);
}

int64_t bq_fetch(void* handle, void* out, uint64_t out_cap) {
  BlockingQueue* q = static_cast<BlockingQueue*>(handle);
  std::unique_lock<std::mutex> lock(q->mu);
  if (q->items.empty()) return -1;
  Buf b = q->items.front();
  if (b.size > out_cap) return -3;
  q->items.pop_front();
  std::memcpy(out, b.ptr, b.size);
  arena_free(q->arena, b.ptr);
  q->not_full.notify_one();
  return static_cast<int64_t>(b.size);
}

uint64_t bq_size(void* handle) {
  BlockingQueue* q = static_cast<BlockingQueue*>(handle);
  std::lock_guard<std::mutex> lock(q->mu);
  return q->items.size();
}

void bq_close(void* handle) {
  BlockingQueue* q = static_cast<BlockingQueue*>(handle);
  std::lock_guard<std::mutex> lock(q->mu);
  q->closed = true;
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

void bq_destroy(void* handle) {
  BlockingQueue* q = static_cast<BlockingQueue*>(handle);
  bq_close(handle);
  for (auto& b : q->items) arena_free(q->arena, b.ptr);
  arena_destroy(q->arena);
  delete q;
}

// ---------------------------------------------------------------------------
// Profiler: host event ring + chrome-trace export
// ---------------------------------------------------------------------------

struct ProfEvent {
  char name[64];
  int64_t start_ns;
  int64_t end_ns;
  int64_t tid;
};

struct Profiler {
  std::mutex mu;
  std::vector<ProfEvent> events;
  size_t capacity;
  std::atomic<bool> enabled{false};
};

static Profiler g_prof;

void prof_enable(uint64_t capacity) {
  std::lock_guard<std::mutex> lock(g_prof.mu);
  g_prof.capacity = capacity ? capacity : (1u << 20);
  g_prof.events.clear();
  g_prof.events.reserve(std::min<size_t>(g_prof.capacity, 4096));
  g_prof.enabled = true;
}

void prof_disable() { g_prof.enabled = false; }

int prof_is_enabled() { return g_prof.enabled ? 1 : 0; }

int64_t prof_now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

void prof_record(const char* name, int64_t start_ns, int64_t end_ns,
                 int64_t tid) {
  if (!g_prof.enabled) return;
  std::lock_guard<std::mutex> lock(g_prof.mu);
  if (g_prof.events.size() >= g_prof.capacity) return;  // ring full: drop
  ProfEvent e;
  std::strncpy(e.name, name, sizeof(e.name) - 1);
  e.name[sizeof(e.name) - 1] = '\0';
  e.start_ns = start_ns;
  e.end_ns = end_ns;
  e.tid = tid;
  g_prof.events.push_back(e);
}

uint64_t prof_event_count() {
  std::lock_guard<std::mutex> lock(g_prof.mu);
  return g_prof.events.size();
}

// serialize into caller buffer as chrome-trace JSON; returns bytes
// written or -needed if too small
int64_t prof_dump_json(char* out, uint64_t cap) {
  std::lock_guard<std::mutex> lock(g_prof.mu);
  std::string s = "{\"traceEvents\":[";
  char line[256];
  bool first = true;
  for (const auto& e : g_prof.events) {
    std::snprintf(line, sizeof(line),
                  "%s{\"name\":\"%s\",\"ph\":\"X\",\"pid\":0,\"tid\":%lld,"
                  "\"ts\":%.3f,\"dur\":%.3f}",
                  first ? "" : ",", e.name,
                  static_cast<long long>(e.tid), e.start_ns / 1000.0,
                  (e.end_ns - e.start_ns) / 1000.0);
    s += line;
    first = false;
  }
  s += "]}";
  if (s.size() + 1 > cap) return -static_cast<int64_t>(s.size() + 1);
  std::memcpy(out, s.c_str(), s.size() + 1);
  return static_cast<int64_t>(s.size());
}

// ---------------------------------------------------------------------------
// StatRegistry: named int64 counters (platform/monitor.h:77)
// ---------------------------------------------------------------------------

struct Stats {
  std::mutex mu;
  std::map<std::string, int64_t> vals;
};

static Stats g_stats;

void stat_add(const char* name, int64_t delta) {
  std::lock_guard<std::mutex> lock(g_stats.mu);
  g_stats.vals[name] += delta;
}

int64_t stat_get(const char* name) {
  std::lock_guard<std::mutex> lock(g_stats.mu);
  auto it = g_stats.vals.find(name);
  return it == g_stats.vals.end() ? 0 : it->second;
}

void stat_reset(const char* name) {
  std::lock_guard<std::mutex> lock(g_stats.mu);
  if (name && *name) {
    g_stats.vals.erase(name);
  } else {
    g_stats.vals.clear();
  }
}

}  // extern "C"

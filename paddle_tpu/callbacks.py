"""paddle.callbacks namespace (reference python/paddle/callbacks.py)."""
from .hapi.callbacks import (Callback, ProgBarLogger,  # noqa: F401
                             ModelCheckpoint, EarlyStopping, VisualDL,
                             ProfilerCallback,
                             LRSchedulerCallback as LRScheduler)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL", "ProfilerCallback"]

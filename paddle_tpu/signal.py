"""``paddle_tpu.signal`` — short-time Fourier analysis.

Reference parity: ``python/paddle/signal.py`` (frame / overlap_add /
stft / istft, built there on ``operators/frame_op.cc``,
``overlap_add_op.cc`` and the spectral ops).  Here frame is a gather,
overlap_add a scatter-add, and the FFTs are XLA HLO — all fully
jit-traceable with static frame counts.
"""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import dispatch
from .core.tensor import Tensor, to_tensor

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_idx(n, frame_length, hop_length):
    if n < frame_length:
        raise ValueError(
            f"input size ({n}) < frame_length ({frame_length})")
    num_frames = 1 + (n - frame_length) // hop_length
    return (jnp.arange(frame_length)[:, None]
            + hop_length * jnp.arange(num_frames)[None, :])


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice ``x`` into overlapping frames.

    axis=-1 → output (..., frame_length, num_frames);
    axis=0  → output (num_frames, frame_length, ...).
    Reference: ``python/paddle/signal.py`` frame().
    """
    x = to_tensor(x)
    if hop_length is None or hop_length <= 0:
        raise ValueError("hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError(f"axis should be 0 or -1, got {axis}")
    n = x.shape[0] if axis == 0 else x.shape[-1]
    idx = _frame_idx(n, frame_length, hop_length)

    def impl(a):
        if axis == 0:
            # (num_frames, frame_length, ...)
            return a[jnp.transpose(idx)]
        return a[..., idx]
    return dispatch("frame", impl, (x,), {})


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of :func:`frame` — scatter-add overlapping frames.

    Reference: ``python/paddle/signal.py`` overlap_add()
    (``operators/overlap_add_op.cc``).
    """
    x = to_tensor(x)
    if hop_length is None or hop_length <= 0:
        raise ValueError("hop_length must be positive")
    if axis not in (0, -1):
        raise ValueError(f"axis should be 0 or -1, got {axis}")
    if axis == 0:
        num_frames, frame_length = x.shape[0], x.shape[1]
    else:
        frame_length, num_frames = x.shape[-2], x.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    idx = _frame_idx(out_len, frame_length, hop_length)

    def impl(a):
        if axis == 0:
            shape = (out_len,) + a.shape[2:]
            out = jnp.zeros(shape, a.dtype)
            return out.at[jnp.transpose(idx)].add(a)
        shape = a.shape[:-2] + (out_len,)
        out = jnp.zeros(shape, a.dtype)
        return out.at[..., idx].add(a)
    return dispatch("overlap_add", impl, (x,), {})


def _prep_window(window, win_length, n_fft, dtype):
    if win_length > n_fft:
        raise ValueError(
            f"win_length ({win_length}) must be <= n_fft ({n_fft})")
    if window is None:
        w = jnp.ones((win_length,), dtype)
    else:
        w = window._data if isinstance(window, Tensor) else jnp.asarray(window)
        if w.shape[0] != win_length:
            raise ValueError(
                f"window length ({w.shape[0]}) != win_length ({win_length})")
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        w = jnp.pad(w, (pad, n_fft - win_length - pad))
    return w


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """Short-time Fourier transform.

    Output: complex (..., n_fft//2+1 if onesided else n_fft, num_frames).
    Reference: ``python/paddle/signal.py`` stft().
    """
    x = to_tensor(x)
    hop_length = n_fft // 4 if hop_length is None else hop_length
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    win_length = n_fft if win_length is None else win_length
    w = _prep_window(window, win_length, n_fft, x._data.real.dtype)
    is_complex = jnp.iscomplexobj(x._data)
    if is_complex and onesided:
        raise ValueError("onesided is not supported for complex input")

    def impl(a):
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        idx = _frame_idx(a.shape[-1], n_fft, hop_length)
        frames = a[..., idx] * w[:, None]
        fftfn = jnp.fft.rfft if (onesided and not is_complex) else jnp.fft.fft
        spec = fftfn(frames, n=n_fft, axis=-2)
        if normalized:
            spec = spec * (1.0 / jnp.sqrt(n_fft).astype(spec.real.dtype))
        return spec
    return dispatch("stft", impl, (x,), {})


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope normalization.

    Reference: ``python/paddle/signal.py`` istft().
    """
    x = to_tensor(x)
    hop_length = n_fft // 4 if hop_length is None else hop_length
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    win_length = n_fft if win_length is None else win_length
    if onesided and return_complex:
        raise ValueError(
            "onesided=True is incompatible with return_complex=True")
    w = _prep_window(window, win_length, n_fft, jnp.float32)

    # NOLA validation (eager: shapes and window are concrete here).  The
    # reference raises when the squared-window overlap-add envelope has
    # ~zero entries in the retained region.
    import numpy as _nmp
    _frames = x.shape[-1]
    _out_len = (_frames - 1) * hop_length + n_fft
    _idx = _nmp.asarray(_frame_idx(_out_len, n_fft, hop_length))
    _env_full = _nmp.zeros(_out_len)
    _nmp.add.at(_env_full, _idx.reshape(-1),
                _nmp.broadcast_to(_nmp.asarray(w * w)[:, None],
                                  _idx.shape).reshape(-1))
    _env = _env_full
    if center:
        _env = _env[n_fft // 2: _out_len - n_fft // 2]
    if length is not None:
        _env = _env[:length]
    if _env.size and _nmp.abs(_env).min() < 1e-11:
        raise ValueError(
            "window/hop_length pair violates NOLA: overlap-add envelope "
            "has (near-)zero entries; istft is not invertible")

    def impl(spec):
        if normalized:
            spec = spec * jnp.sqrt(n_fft).astype(spec.real.dtype)
        if onesided and not return_complex:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(spec, n=n_fft, axis=-2)
            if not return_complex:
                frames = frames.real
        frames = frames * w[:, None]
        num_frames = frames.shape[-1]
        out_len = (num_frames - 1) * hop_length + n_fft
        idx = _frame_idx(out_len, n_fft, hop_length)
        out = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
        out = out.at[..., idx].add(frames)
        # COLA normalization by the precomputed (compile-time) envelope
        env = jnp.asarray(_env_full, w.dtype)
        out = out / jnp.where(env > 1e-11, env, 1.0)
        if center:
            out = out[..., n_fft // 2: out_len - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out
    return dispatch("istft", impl, (x,), {})

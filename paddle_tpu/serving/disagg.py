"""Disaggregated prefill/decode serving — the transfer client.

Prefill is compute-bound (one big chunked-attention pass over the
prompt), decode is memory-bound (one token per step against a growing
KV cache); a monolithic replica sizes both against the same chip.
Disaggregation splits the fleet by role (``FleetReplica(role=...)``):

- ``prefill`` replicas run chunked prefill and ship the filled KV
  block chain to peers (``/admin/kv/prefill``).  The router never
  sends them client traffic, and their frontend sheds full-decode
  requests typed (``reason="wrong_role"``).
- ``decode`` replicas take the client traffic.  Before each local
  prefill, :class:`DisaggClient` pulls the prompt's chain from the
  least-loaded prefill peer and adopts it through
  ``PagedGenerationEngine.import_prefix_chain`` — after which the
  normal prefix-cache admission path sees a hit and prefills only the
  uncovered suffix.
- ``both`` (default) is the monolith, bit-for-bit the pre-disagg
  behavior.

Every transfer is best-effort and fail-closed: the blob is
sha256-verified on receive (``generation/kv_wire.py``), and ANY
failure — connection loss (the ``kv.transfer`` chaos site injects
exactly this), a corrupt shipment, pool exhaustion on the receiver —
counts ``kv.transfer.fail`` and falls back to a local re-prefill.  A
transfer can cost latency; it can never lose a request or decode over
wrong KV.  Decode output is bit-exact vs the monolith because the
adopted chain is bit-identical KV and sampling keys are per-absolute-
position (``fold_in(key, position)``) — where the prefill ran is
invisible to the stream.

Metrics: ``kv.transfer.fetch`` / ``.bytes`` / ``.ms`` (pull path),
``kv.transfer.fail`` (any fallback), ``kv.transfer.corrupt``
(verification rejections, counted in ``kv_wire``).
"""
from __future__ import annotations

import base64
import http.client
import json
import time
from typing import Optional

import numpy as np

from ..profiler import flight as _flight
from ..utils import chaos as _chaos
from .fleet import list_replicas

__all__ = ["DisaggClient"]


class DisaggClient:
    """Pulls prefilled KV chains from prefill-role peers into a local
    :class:`~.engine.PagedGenerationEngine` (the decode side of the
    disaggregated fleet)."""

    def __init__(self, store, job: str, engine, *,
                 replica_id: Optional[str] = None,
                 timeout: float = 30.0):
        self.store = store
        self.job = str(job)
        self.engine = engine
        self.replica_id = replica_id
        self.timeout = float(timeout)
        from ..profiler import metrics as _metrics
        self._m_fetch = _metrics.counter(
            "kv.transfer.fetch", "KV chains pulled from a prefill "
            "peer and adopted into the local pool")
        self._m_bytes = _metrics.counter(
            "kv.transfer.bytes", "wire bytes of adopted KV chain "
            "blobs (header + verified payload)")
        self._m_fail = _metrics.counter(
            "kv.transfer.fail", "KV chain pulls abandoned (peer "
            "unreachable, chaos, corrupt blob, pool exhaustion) — "
            "each one a clean local re-prefill, zero lost requests")
        self._h_ms = _metrics.histogram(
            "kv.transfer.ms", "end-to-end chain pull latency "
            "(HTTP round trip + verify + adopt), ms")

    # -- peer choice ---------------------------------------------------
    def _pick_peer(self):
        """Least-loaded ready prefill-capable peer, or None."""
        try:
            infos = list_replicas(self.store, self.job)
        except Exception:   # noqa: BLE001 — registry outage = no peer
            return None
        cands = [i for i in infos.values()
                 if i.role in ("prefill", "both") and i.ready
                 and i.endpoint and i.replica_id != self.replica_id]
        if not cands:
            return None
        return min(cands, key=lambda i: (i.load(), i.replica_id))

    # -- the pull ------------------------------------------------------
    def ensure_chain(self, prompt_ids) -> bool:
        """Make the prompt's prefix chain locally cached, pulling it
        from a prefill peer when worthwhile.  Returns True when a
        chain was adopted; False means the caller's own prefill does
        the work (already cached, no peer, or any transfer failure —
        never raises)."""
        toks = np.ascontiguousarray(prompt_ids,
                                    dtype=np.int32).reshape(-1)
        plen = int(toks.size)
        eng = self.engine
        bs = eng.session.block_size
        if plen < 1:
            return False
        # local probe: skip the wire when the cache already covers the
        # prompt to within one block (the transfer would save nothing
        # the suffix prefill doesn't redo anyway)
        chain, covered = eng.prefix_cache.lookup(toks)
        if chain:
            eng.pool.decref(chain)
        if plen - covered <= bs:
            return False
        peer = self._pick_peer()
        if peer is None:
            return False
        t0 = time.monotonic()
        try:
            if _chaos.active:
                _chaos.hit("kv.transfer", exc=ConnectionResetError)
            blob = self._fetch(peer, toks)
            covered = eng.import_prefix_chain(blob)
        except Exception as e:  # noqa: BLE001 — fall back to local prefill
            self._m_fail.inc()
            if _flight.active:
                _flight.note("kv", "transfer_fail", peer=peer.replica_id,
                             error=f"{type(e).__name__}: {e}")
            return False
        ms = (time.monotonic() - t0) * 1e3
        self._m_fetch.inc()
        self._m_bytes.inc(len(blob))
        self._h_ms.observe(ms)
        if _flight.active:
            _flight.note("kv", "transfer", peer=peer.replica_id,
                         covered=int(covered), bytes=len(blob),
                         ms=round(ms, 3))
        return True

    def _fetch(self, peer, toks: np.ndarray) -> bytes:
        """One ``/admin/kv/prefill`` round trip; raises on anything
        short of a verified 200 + blob."""
        host, port = peer.endpoint.rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port),
                                          timeout=self.timeout)
        try:
            body = json.dumps({"prompt_ids": toks.tolist()}).encode()
            conn.request("POST", "/admin/kv/prefill", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
        finally:
            conn.close()
        if resp.status != 200:
            raise RuntimeError(
                f"peer {peer.replica_id} answered {resp.status}: "
                f"{data[:200]!r}")
        doc = json.loads(data.decode())
        return base64.b64decode(doc["blob"])

"""``paddle_tpu.serving`` — turn a saved inference artifact into a
servable endpoint.

The synchronous ``paddle_tpu.inference.Predictor`` answers one request
per dispatch.  This package adds the serving layer the ROADMAP's
"heavy traffic from millions of users" goal needs (reference parity:
Paddle's AnalysisPredictor + Clone multi-threaded serving stack, grown
with Orca/Clipper-style dynamic batching):

- :class:`InferenceEngine` (``engine.py``): bounded request queue,
  dynamic batcher (``max_batch_size`` / ``batch_timeout_ms``), futures
  fan-out, pool of ``Predictor.clone()`` workers sharing one set of
  device weights;
- shape bucketing + compiled-executable cache (``bucketing.py``):
  total XLA compiles bounded by the bucket count, not by observed
  shapes;
- admission control (``admission.py``): queue-depth bound, per-request
  deadlines, explicit overload rejection with SLO metrics, priority
  classes (``interactive``/``standard``/``batch``) with bounded-aging
  dequeue, per-tenant token-bucket quotas
  (:class:`TenantQuotaTable`, hot-reloadable via
  :class:`QuotaWatcher`), drain-rate-derived Retry-After
  (:class:`DrainRateEstimator`);
- HTTP frontend (``server.py``): ``/v1/infer`` (JSON or .npz),
  ``/v1/generate`` (JSON; SSE token streaming with ``stream=true``),
  ``/healthz``, Prometheus ``/metrics``;
- :class:`GenerationEngine` (``engine.py``): continuous (in-flight)
  batching for autoregressive decode — a slot-based scheduler admits
  queued prompts into the running batch at token boundaries over a
  fixed-capacity KV-cache (``paddle_tpu.generation``), retires
  finished rows without draining the batch, streams tokens per
  request, and extends admission to token budgets;
- the serving fleet (``fleet.py``): :class:`FleetReplica` (engine +
  HTTP server + TTL-lease registry heartbeat + manifest-v2 weight
  watcher), :class:`FleetRouter` (least-loaded dispatch, transport
  failover, probe-driven denylist, canary-then-promote weight
  hot-swaps with rollback) — the multi-host scale-out and
  zero-downtime-rollout tier.

Quick start::

    from paddle_tpu import serving
    engine = serving.InferenceEngine("model", serving.EngineConfig(
        max_batch_size=16, batch_timeout_ms=3, num_workers=2))
    out, = engine.infer([x])              # in-process
    serving.ServingServer(engine).start() # ... or over HTTP

    gen = serving.GenerationEngine(gpt, serving.GenerationEngineConfig(
        max_slots=8, max_new_tokens=128))
    for tok in gen.submit(prompt_ids, do_sample=True, seed=7):
        ...                               # tokens as they decode
"""
from .admission import (PRIORITIES, AdmissionController,
                        DeadlineExceeded, DrainRateEstimator,
                        EngineClosed, QuotaWatcher, RequestRejected,
                        TenantQuotaTable, priority_rank)
from .bucketing import BucketPolicy, ExecutableCache, next_bucket, \
    pad_batch, seq_buckets
from .disagg import DisaggClient
from .engine import (EngineConfig, GenerationEngine,
                     GenerationEngineConfig, GenerationStream,
                     InferenceEngine, PagedGenerationEngine,
                     validate_artifact)
from .fleet import (FleetReplica, FleetRouter, ReplicaRegistry,
                    WeightWatcher)
from .server import ServingServer, serve

__all__ = ["InferenceEngine", "EngineConfig", "ServingServer", "serve",
           "GenerationEngine", "GenerationEngineConfig",
           "GenerationStream", "PagedGenerationEngine",
           "RequestRejected", "DeadlineExceeded",
           "EngineClosed", "AdmissionController", "BucketPolicy",
           "ExecutableCache", "next_bucket", "pad_batch",
           "seq_buckets", "validate_artifact", "FleetReplica",
           "FleetRouter", "ReplicaRegistry", "WeightWatcher",
           "PRIORITIES", "priority_rank", "TenantQuotaTable",
           "DrainRateEstimator", "QuotaWatcher", "DisaggClient"]

"""Stdlib HTTP frontend for the serving engines.

No framework dependency — ``http.server.ThreadingHTTPServer`` is enough
because the engines already own queueing, batching, and admission; each
HTTP handler thread just parks on its request future (or relays a token
stream).  Endpoints:

    POST /v1/infer    JSON  {"inputs": {name: nested-list}, ...}
                      or an .npz body (Content-Type application/x-npz)
                      with one array per input name
    POST /v1/generate JSON  {"prompt_ids": [...], "max_new_tokens": n,
                      "do_sample": bool, "temperature": t, "top_k": k,
                      "top_p": p, "seed": s, "eos_token_id": id,
                      "stream": bool} against a GenerationEngine.
                      ``stream=false`` answers one JSON object
                      {"tokens": [...]} when generation finishes;
                      ``stream=true`` answers ``text/event-stream``
                      over chunked transfer — one ``data:`` event per
                      sampled token as the continuous batcher emits it
                      ({"token": id, "index": n}), then a final
                      {"done": true, "tokens": [...]} event.  Errors
                      after streaming began arrive as a terminal
                      {"error": ...} event (the status line already
                      went out).
    GET  /healthz     liveness + pool/queue snapshot (JSON).  The
                      ``ready`` field distinguishes *dispatchable*
                      from merely alive: false while warmup buckets
                      are still compiling (and after close) — fleet
                      routers skip not-ready replicas
    POST /admin/swap  fleet control plane (bound only by
                      fleet.FleetReplica): verify + hot-swap weights
                      to a checkpoint step
    GET  /metrics     Prometheus text exposition of the whole profiler
                      metrics registry (PR 1 exporter)

Status mapping: 200 ok, 400 malformed payload, 429 admission rejection
(overload — shed, don't OOM), 503 engine closed, 504 deadline exceeded.
429/503 responses carry a ``Retry-After`` header derived from the
observed queue drain rate (clamped to [1, 30] s), not a constant.

Multi-tenant SLO headers (``/v1/generate``): ``X-Tenant`` charges the
request against that tenant's token bucket and labels its
``serving.tenant.*`` metric series; ``X-Priority`` is one of
``interactive`` / ``standard`` / ``batch`` and orders dequeue (payload
keys ``tenant`` / ``priority`` work too; the headers win).  Quota
exhaustion answers 429 with ``reason="tenant_quota"``.
"""
from __future__ import annotations

import io
import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from ..profiler import rtrace as _rtrace
from ..profiler import tracer as _tracer
from .admission import DeadlineExceeded, EngineClosed, RequestRejected

__all__ = ["ServingServer", "serve"]


def _json_default(o):
    if isinstance(o, np.ndarray):
        return o.tolist()
    if isinstance(o, (np.generic,)):
        return o.item()
    raise TypeError(f"not JSON serializable: {type(o).__name__}")


class _Handler(BaseHTTPRequestHandler):
    # the engine is attached to the server object by ServingServer
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default
        if getattr(self.server, "verbose", False):  # pragma: no cover
            super().log_message(fmt, *args)

    # -- request observability -----------------------------------------
    def _begin_request(self):
        """Per-request identity: honor ``X-Request-Id`` (generate one
        when absent) and build the rtrace TraceContext from the W3C
        ``traceparent`` header.  Both are echoed on every response —
        including SSE terminal events and error payloads — so a client
        can always join its logs to the server's trace.  Also counts
        the request into the server's in-flight tally, which the
        graceful-drain path (``ServingServer.stop``) waits on."""
        srv = self.server
        with srv._drain_cond:
            srv._active_requests += 1
        rid = self.headers.get("X-Request-Id") or uuid.uuid4().hex
        self._request_id = rid
        self._obs_headers = {"X-Request-Id": rid}
        tp = self.headers.get("traceparent")
        self._ctx = _rtrace.TraceContext.from_headers(tp,
                                                      request_id=rid)
        self._traced = _rtrace.active
        if self._traced:
            self._obs_headers["traceparent"] = self._ctx.traceparent()
            self._t_ingress = _tracer.now_ns()
        elif tp:
            # tracing off: echo the caller's context untouched so the
            # distributed trace is not silently broken mid-chain
            self._obs_headers["traceparent"] = tp
        self._t_first_write = None
        self._last_status = None

    def _end_request(self):
        """Close the request's server-side spans: ``egress`` (first
        response byte -> done) and the ``ingress`` root (header parse
        -> done, parented to the client's traceparent span) — and
        release the in-flight drain tally."""
        srv = self.server
        with srv._drain_cond:
            srv._active_requests -= 1
            srv._drain_cond.notify_all()
        if not getattr(self, "_traced", False):
            return
        t1 = _tracer.now_ns()
        path = self.path
        self._ctx.record("egress", self._t_first_write or t1, t1,
                         status=self._last_status)
        self._ctx.record("ingress", self._t_ingress, t1, parent=None,
                         span_id=self._ctx.root, path=path,
                         status=self._last_status)

    # -- helpers -------------------------------------------------------
    def _send(self, code: int, body: bytes, ctype: str,
              retry_after=None):
        if self._t_first_write is None:
            self._t_first_write = _tracer.now_ns() \
                if getattr(self, "_traced", False) else 0
        self._last_status = code
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", str(int(retry_after)))
        for k, v in getattr(self, "_obs_headers", {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, obj, retry_after=None):
        if code >= 400 and isinstance(obj, dict) \
                and "request_id" not in obj \
                and getattr(self, "_request_id", None):
            # error payloads carry the id in-band too: a client that
            # only logs bodies can still quote it at the operator
            obj = dict(obj, request_id=self._request_id)
        self._send(code, json.dumps(obj, default=_json_default)
                   .encode(), "application/json",
                   retry_after=retry_after)

    # -- GET -----------------------------------------------------------
    def do_GET(self):  # noqa: N802 - stdlib handler naming
        self._begin_request()
        try:
            self._do_get()
        finally:
            self._end_request()

    def _do_get(self):
        engine = self.server.engine or self.server.generation_engine
        if self.path == "/healthz":
            from ..profiler import metrics as _metrics
            depth = _metrics.get(
                getattr(engine, "metrics_prefix", "serving")
                + ".queue_depth")
            # alive != dispatchable: ``ready`` stays false while an
            # engine is still compiling warmup buckets (or draining at
            # shutdown) — a router must not dispatch into cold
            # compiles, so it treats not-ready replicas as
            # undispatchable while this endpoint keeps answering 200
            ready = all(
                getattr(e, "ready", True)
                for e in (self.server.engine,
                          self.server.generation_engine)
                if e is not None)
            body = {"status": "ok", "ready": ready,
                    "queue_depth": depth.value if depth else 0}
            admin = getattr(self.server, "fleet_admin", None)
            if admin is not None:
                # fleet replica: weight provenance rides /healthz so
                # the router's canary controller needs no extra RPC
                body.update(admin.health_fields())
            if self.server.engine is not None:
                e = self.server.engine
                body.update(model_inputs=e.input_names,
                            workers=e.config.num_workers,
                            max_batch_size=e.config.max_batch_size,
                            warmed_buckets=getattr(e, "warmed_buckets",
                                                   0))
            g = self.server.generation_engine
            if g is not None:
                # distinct key: with both engines bound, the decode
                # warmup count must not clobber the batch engine's
                body.update(decode_slots=g.slots,
                            max_length=g.max_length,
                            decode_warmed_buckets=getattr(
                                g, "warmed_buckets", 0),
                            requests_parked=getattr(g, "parked", 0))
                pool = getattr(g, "pool", None)
                if pool is not None:
                    # paged engine: block-pool occupancy + prefix-cache
                    # effectiveness are THE capacity signals a router /
                    # autoscaler dispatches on
                    p = g.metrics_prefix

                    def _val(name):
                        m = _metrics.get(f"{p}.{name}")
                        return m.value if m is not None else 0
                    hits = _val("prefix_cache.hit")
                    misses = _val("prefix_cache.miss")
                    body.update(
                        kv_blocks_total=pool.num_blocks,
                        kv_blocks_in_flight=pool.used,
                        kv_blocks_free=pool.available,
                        kv_block_size=pool.block_size,
                        prefix_cache_hit_rate=round(
                            hits / (hits + misses), 6)
                        if (hits + misses) else 0.0)
            # memory breakdown (whichever engine answers): where HBM
            # went — params / kv arena / prefix cache / step peak —
            # so capacity planning reads the ceiling off /healthz
            mb = getattr(g if g is not None else engine,
                         "memory_breakdown", None)
            if mb is not None:
                try:
                    body.update(mb())
                except Exception:   # noqa: BLE001 — health never 500s
                    pass
            self._send_json(200, body)
        elif self.path == "/metrics":
            from ..profiler import metrics as _metrics
            self._send(200, _metrics.prometheus_text().encode(),
                       "text/plain; version=0.0.4")
        else:
            self._send_json(404, {"error": f"no route {self.path}; "
                                  "try /v1/infer, /healthz, /metrics"})

    # -- POST ----------------------------------------------------------
    def do_POST(self):  # noqa: N802
        self._begin_request()
        try:
            self._do_post()
        finally:
            self._end_request()

    def _do_post(self):
        if self.path in ("/v1/generate", "/generate"):
            self._do_generate()
            return
        if self.path.startswith("/admin/"):
            self._do_admin()
            return
        if self.path not in ("/v1/infer", "/infer"):
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        engine = self.server.engine
        if engine is None:
            self._send_json(404, {"error": "no inference engine bound; "
                                  "this server only serves /v1/generate"})
            return
        try:
            body = self._read_body()
            if body is None:
                return
            ctype = (self.headers.get("Content-Type") or "").lower()
            deadline_ms = self.headers.get("X-Deadline-Ms")
            if "json" in ctype or not ctype:
                payload = json.loads(body.decode() or "{}")
                inputs = payload.get("inputs", payload)
                if deadline_ms is None:
                    deadline_ms = payload.get("deadline_ms")
                inputs = self._decode_json_inputs(engine, inputs)
                as_npz = False
            else:  # .npz / binary payload
                with np.load(io.BytesIO(body), allow_pickle=False) as z:
                    inputs = {n: z[n] for n in z.files}
                as_npz = True
        except Exception as e:
            self._send_json(400, {"error": f"malformed payload: {e}"})
            return
        try:
            kwargs = {"trace_ctx": self._ctx}
            if deadline_ms is not None:
                kwargs["deadline_ms"] = float(deadline_ms)
            outs = engine.infer(inputs, **kwargs)
        except EngineClosed as e:
            self._send_json(503, {"error": str(e), "reason": e.reason},
                            retry_after=getattr(e, "retry_after", None))
            return
        except RequestRejected as e:
            self._send_json(429, {"error": str(e), "reason": e.reason},
                            retry_after=getattr(e, "retry_after", None))
            return
        except DeadlineExceeded as e:
            self._send_json(504, {"error": str(e),
                                  "reason": getattr(e, "reason",
                                                    "deadline")})
            return
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
            return
        except Exception as e:  # model-side failure
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        names = [f"output_{i}" for i in range(len(outs))] \
            if not engine._base._output_names else \
            list(engine._base._output_names)
        if as_npz:
            buf = io.BytesIO()
            np.savez(buf, **dict(zip(names, outs)))
            self._send(200, buf.getvalue(), "application/x-npz")
        else:
            self._send_json(200, {"outputs": dict(zip(names, outs))})

    def _do_admin(self):
        """Fleet control plane (``/admin/swap``): available only when a
        fleet admin object (a ``serving.fleet.FleetReplica``) is bound.
        The router drives the canary/promote/rollback flow through
        this endpoint; it is NOT exposed by a bare ServingServer."""
        admin = getattr(self.server, "fleet_admin", None)
        if admin is None:
            self._send_json(404, {"error": "no fleet admin bound "
                                  "(serve via fleet.FleetReplica)"})
            return
        try:
            body = self._read_body()
            if body is None:
                return
            payload = json.loads(body.decode() or "{}")
        except Exception as e:
            self._send_json(400, {"error": f"malformed payload: {e}"})
            return
        try:
            code, obj = admin.admin_request(self.path, payload)
        except Exception as e:   # noqa: BLE001 — control plane must answer
            code, obj = 500, {"error": f"{type(e).__name__}: {e}"}
        self._send_json(code, obj)

    def _read_body(self):
        """Read the request body under the server byte cap.  Returns
        the bytes, or None after answering 413 — shed, don't OOM: the
        admission contract applies to payload bytes too, BEFORE
        buffering (shared by /v1/infer and /v1/generate)."""
        length = int(self.headers.get("Content-Length") or 0)
        cap = getattr(self.server, "max_body_bytes", 0)
        if cap and length > cap:
            # the body stays unread by design; close the keep-alive
            # connection so the leftover bytes can't be misparsed as
            # the next pipelined request
            self.close_connection = True
            self._send_json(413, {
                "error": f"request body {length} bytes exceeds the "
                         f"server cap {cap}",
                "reason": "body_too_large"})
            return None
        return self.rfile.read(length)

    # -- generation (streaming) ----------------------------------------
    def _do_generate(self):
        gen = self.server.generation_engine
        if gen is None:
            self._send_json(404, {"error": "no generation engine bound "
                                  "(serve a model via GenerationEngine)"})
            return
        try:
            body = self._read_body()
            if body is None:
                return
            payload = json.loads(body.decode() or "{}")
            prompt = payload.get("prompt_ids", payload.get("prompt"))
            if prompt is None:
                raise ValueError("missing 'prompt_ids'")
            prompt = np.asarray(prompt, np.int32).reshape(-1)
            kw = {}
            for k in ("max_new_tokens", "top_k", "seed",
                      "eos_token_id"):
                if payload.get(k) is not None:
                    kw[k] = int(payload[k])
            for k in ("temperature", "top_p", "deadline_ms"):
                if payload.get(k) is not None:
                    kw[k] = float(payload[k])
            if self.headers.get("X-Deadline-Ms") is not None:
                kw["deadline_ms"] = float(self.headers["X-Deadline-Ms"])
            kw["do_sample"] = bool(payload.get("do_sample", False))
            # multi-tenant SLO identity: headers win over payload keys
            # (a gateway stamping X-Tenant must not be overridable by
            # the request body)
            tenant = self.headers.get("X-Tenant") \
                or payload.get("tenant")
            if tenant is not None:
                kw["tenant"] = str(tenant)
            prio = self.headers.get("X-Priority") \
                or payload.get("priority")
            if prio is not None:
                kw["priority"] = str(prio)
            stream = bool(payload.get("stream", False))
        except Exception as e:
            self._send_json(400, {"error": f"malformed payload: {e}"})
            return
        if getattr(self.server, "role", "both") == "prefill":
            # a prefill-role pool never runs full decodes: shed typed
            # through the standard admission path (counted
            # request.rejected.wrong_role + Retry-After) — the router
            # already filters prefill replicas out, so landing here
            # means a stale client or a misconfigured fleet
            try:
                gen._admission.reject(
                    "wrong_role",
                    "replica runs role=prefill; decode requests "
                    "belong on a decode or both replica",
                    tenant=kw.get("tenant"), priority=kw.get("priority"))
            except RequestRejected as e:
                self._send_json(429, {"error": str(e),
                                      "reason": e.reason},
                                retry_after=getattr(e, "retry_after",
                                                    None))
                return
        pf = getattr(self.server, "kv_prefetch", None)
        if pf is not None:
            # best-effort: a failed pull (counted kv.transfer.fail)
            # just means the local prefill does the work
            try:
                pf(prompt)
            except Exception:   # noqa: BLE001 — never blocks serving
                pass
        try:
            handle = gen.submit(prompt, trace_ctx=self._ctx, **kw)
        except EngineClosed as e:
            self._send_json(503, {"error": str(e), "reason": e.reason},
                            retry_after=getattr(e, "retry_after", None))
            return
        except RequestRejected as e:
            self._send_json(429, {"error": str(e), "reason": e.reason},
                            retry_after=getattr(e, "retry_after", None))
            return
        except ValueError as e:
            self._send_json(400, {"error": str(e)})
            return
        except Exception as e:   # chaos injection / model-side failure
            self._send_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        if not stream:
            try:
                toks = handle.result()
            except DeadlineExceeded as e:
                self._send_json(504, {"error": str(e),
                                      "reason": getattr(e, "reason",
                                                        "deadline")})
                return
            except Exception as e:
                self._send_json(500,
                                {"error": f"{type(e).__name__}: {e}"})
                return
            self._send_json(200, {"tokens": toks.tolist()})
            return
        # SSE over chunked transfer: the status goes out before the
        # request finishes, so late errors become a terminal event
        if self._t_first_write is None:
            self._t_first_write = _tracer.now_ns() if self._traced \
                else 0
        self._last_status = 200
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        for k, v in self._obs_headers.items():
            self.send_header(k, v)
        self.end_headers()
        rid = self._request_id
        try:
            i = 0
            for tok in handle:
                self._chunk(f"data: {json.dumps({'token': int(tok), 'index': i})}\n\n")
                i += 1
            # terminal event carries the request id: SSE consumers
            # often never see response headers through proxies/polyfills
            self._chunk("data: " + json.dumps(
                {"done": True, "request_id": rid,
                 "tokens": handle.result().tolist()}) + "\n\n")
        except OSError:
            # client went away mid-stream: free the decode slot and the
            # token-budget reservation at the next token boundary
            # instead of generating the rest for nobody
            handle.cancel()
            return
        except Exception as e:
            try:
                self._chunk("data: " + json.dumps(
                    {"error": f"{type(e).__name__}: {e}",
                     "request_id": rid}) + "\n\n")
            except OSError:
                handle.cancel()
                return
        try:
            self.wfile.write(b"0\r\n\r\n")   # chunked terminator
        except OSError:
            pass

    def _chunk(self, text: str):
        data = text.encode()
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    @staticmethod
    def _decode_json_inputs(engine, inputs):
        if isinstance(inputs, dict):
            return {n: np.asarray(v) for n, v in inputs.items()}
        if isinstance(inputs, list):
            return [np.asarray(v) for v in inputs]
        raise ValueError("'inputs' must be a dict {name: array} or a "
                         "positional list of arrays")


class ServingServer:
    """Owns a ThreadingHTTPServer bound to ``engine``.

    ``start()`` serves on a daemon thread and returns; ``stop()`` shuts
    the listener down *gracefully* — stop accepting, drain in-flight
    requests (including active SSE streams), deregister the replica
    lease when a ``registry`` is attached — but the engine itself is
    NOT closed (callers own its lifecycle, so one engine can outlive
    server restarts).  The ordering contract is that once ``stop``
    returns, closing the engine cannot race a streaming handler."""

    def __init__(self, engine=None, host: str = "127.0.0.1",
                 port: int = 0, verbose: bool = False,
                 max_body_bytes: int = 64 << 20,
                 generation_engine=None, registry=None,
                 fleet_admin=None, role: str = "both",
                 kv_prefetch=None):
        from .engine import GenerationEngine
        if generation_engine is None and isinstance(engine,
                                                    GenerationEngine):
            engine, generation_engine = None, engine
        if engine is None and generation_engine is None:
            raise ValueError("bind at least one engine")
        self.engine = engine
        self.generation_engine = generation_engine
        self.registry = registry
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.engine = engine
        self._httpd.generation_engine = generation_engine
        self._httpd.verbose = verbose
        self._httpd.max_body_bytes = int(max_body_bytes)
        self._httpd.daemon_threads = True
        self._httpd.fleet_admin = fleet_admin
        # disaggregated serving (fleet.py wires these): a prefill-role
        # server sheds full-decode traffic typed; a decode-role server
        # runs kv_prefetch(prompt_ids) — best-effort chain pull from a
        # prefill peer — before every submit
        self._httpd.role = str(role)
        self._httpd.kv_prefetch = kv_prefetch
        self._httpd._active_requests = 0
        self._httpd._drain_cond = threading.Condition()
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ServingServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="serving-http",
            daemon=True)
        self._thread.start()
        return self

    @property
    def active_requests(self) -> int:
        return self._httpd._active_requests

    def stop(self, drain_s: float = 30.0):
        """Graceful shutdown: (1) stop accepting new connections,
        (2) let every in-flight handler — including mid-stream SSE
        responses — run to completion, bounded by ``drain_s`` seconds,
        (3) deregister the replica lease so the router stops routing
        here, (4) release the socket.  Only THEN is it safe for the
        owner to close the engine: the old ``shutdown-then-close``
        sequence could yank the engine out from under an active
        streaming handler mid-token.  ``drain_s=0`` restores the
        immediate (non-draining) behavior."""
        self._httpd.shutdown()
        if drain_s and drain_s > 0:
            deadline = time.monotonic() + drain_s
            with self._httpd._drain_cond:
                while self._httpd._active_requests > 0:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        import warnings
                        warnings.warn(
                            f"ServingServer.stop: "
                            f"{self._httpd._active_requests} request(s)"
                            f" still in flight after the {drain_s}s "
                            "drain window; shutting down anyway",
                            RuntimeWarning)
                        break
                    self._httpd._drain_cond.wait(timeout=remaining)
        if self.registry is not None:
            try:
                self.registry.deregister()
            except Exception as e:  # noqa: BLE001 — lease TTL covers it
                import warnings
                warnings.warn(f"ServingServer.stop: lease deregister "
                              f"failed ({e!r}); the TTL will expire it",
                              RuntimeWarning)
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def serve(model, host: str = "127.0.0.1", port: int = 8975,
          config=None, block: bool = True):
    """One-call endpoint: build an InferenceEngine over ``model`` (path
    prefix / inference.Config / Predictor) and serve it over HTTP.
    ``block=False`` returns the started :class:`ServingServer`."""
    from .engine import GenerationEngine, InferenceEngine
    owns_engine = not isinstance(model, (InferenceEngine,
                                         GenerationEngine))
    engine = model if not owns_engine \
        else InferenceEngine(model, config=config)
    server = ServingServer(engine, host=host, port=port).start()
    if not block:
        return server
    try:  # pragma: no cover - interactive path
        while True:
            import time
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
        if owns_engine:   # a caller-provided engine outlives the server
            engine.close()
    return server

"""Multi-host serving fabric: replica registry, failover router,
zero-downtime weight hot-swap.

Everything below ``serving/`` so far serves from ONE process — one
SIGKILL away from dropping every in-flight stream.  This module is the
scale-out story, built on infrastructure already in-repo:

- **Replica registry** (:class:`ReplicaRegistry`): each engine replica
  claims a generation-prefixed TTL-lease key through the elastic
  ``Store`` (PR 9's rendezvous/lease machinery reused as a serving
  membership plane — ``/paddle/serving/<job>/g<gen>/<replica>``,
  ``distributed.launch.serving_key``) and republishes a JSON payload of
  health / readiness / occupancy / weight provenance on a heartbeat
  cadence.  Store outages degrade: the replica keeps serving (heartbeat
  failures are counted, never raised) and the router keeps its
  last-known membership — membership is advisory, serving never blocks
  on the control plane.

- **Failover router** (:class:`FleetRouter`): an HTTP tier that
  discovers replicas from the registry, dispatches **least-loaded**
  (router-local in-flight + the replica's published queue depth and
  slot occupancy), retries idempotent requests on a *different* replica
  with exponential backoff, and sheds with typed 429/503 +
  ``Retry-After`` before queueing unboundedly.  Failure classification
  rides ``utils/resilience.retry``: connection-refused / reset /
  timeout (and a replica's own 503 — it is draining) are
  **failover-able** transport failures; any other application response
  (400/404/429/500/504) is relayed verbatim — retrying a model error on
  another replica would just repeat it.  Replicas that fail
  ``probe_failures`` consecutive ``/healthz`` probes are drained +
  denylisted (and readmitted when probes recover); replicas whose
  ``/healthz`` reports ``ready=false`` (still compiling warmup buckets,
  or draining) are undispatchable.  SSE token streams fail over
  *mid-stream*: generation is seed-deterministic, so the router
  re-issues the request on a surviving replica and splices — events the
  client already received are skipped by index, the stream continues
  byte-identically.

- **Zero-downtime weight hot-swap** (:class:`WeightWatcher` +
  router canary flow): replicas watch a manifest-v2 checkpoint
  directory (``AsyncCheckpointer`` layout, ``<dir>/<step>/``), verify
  the newest committed step — sha256 manifest + ``_PADDLE_COMMITTED``
  marker, quarantining corrupt trees exactly like
  ``AsyncCheckpointer.restore`` (``_quarantine/<step>``,
  ``ckpt.quarantined``) — and publish it as ``available_step``.
  Markerless (mid-commit) trees are invisible, never loaded, never
  quarantined.  The router's canary controller swaps ONE replica to a
  newly available step (``POST /admin/swap``), watches an error-rate
  window of real traffic on it, then promotes the rest of the fleet —
  or rolls the canary back and blacklists the step on failure.  The
  swap itself is ``engine.swap_weights``: applied *between* engine
  steps (token boundaries for generation, quiesced batches for
  inference), so no stream drops and no executable recompiles.

Flight-recorder events (the fleet gate asserts exact counts):
``replica.join`` / ``replica.leave`` (membership), ``replica.deny`` /
``replica.readmit`` (probe verdicts), ``swap.canary`` /
``swap.promote`` / ``swap.rollback`` / ``swap.abort`` (weight
rollouts).  Chaos sites: ``router.dispatch`` (kills one forward hop as
a connection reset) and ``fleet.lease`` (drops heartbeat puts so a
lease expires) — both one predicate read when disarmed.

Quick start (one process per replica, router anywhere)::

    # replica host
    replica = fleet.FleetReplica(
        generation_engine=engine, store="tcp://coord:4536",
        job="chat", watch_dir="/ckpts/chat")
    replica.run()                      # serves until SIGTERM, drains

    # router host
    router = fleet.FleetRouter("tcp://coord:4536", job="chat").start()
    # clients POST /v1/generate to the router exactly as to a replica
"""
from __future__ import annotations

import errno
import http.client
import json
import os
import shutil
import signal
import socket
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..distributed.launch import SERVING_PREFIX, serving_key
from ..profiler import flight as _flight
from ..utils import chaos as _chaos
from ..utils import concurrency as _conc
from ..utils import resilience as _resilience

__all__ = ["ReplicaInfo", "ReplicaRegistry", "list_replicas",
           "WeightWatcher", "FleetReplica", "FleetRouter",
           "failover_classify", "NoReplicaAvailable"]


def _as_store(store):
    """Accept a Store instance or a spec string (``tcp://host:port`` /
    filestore root)."""
    if isinstance(store, str):
        from ..distributed.fleet.elastic.manager import store_from_spec
        return store_from_spec(store)
    return store


# ---------------------------------------------------------------------------
# membership: TTL-lease replica registry
# ---------------------------------------------------------------------------
class ReplicaInfo:
    """One replica's last-published registry payload, parsed."""

    __slots__ = ("replica_id", "generation", "endpoint", "ready",
                 "queue_depth", "occupancy", "slots", "weights_step",
                 "available_step", "role", "prefix_heads",
                 "block_size", "t")

    def __init__(self, replica_id: str, generation: int = 0,
                 endpoint: str = "", ready: bool = False,
                 queue_depth: int = 0, occupancy: int = 0,
                 slots: int = 0, weights_step: Optional[int] = None,
                 available_step: Optional[int] = None,
                 role: str = "both", prefix_heads: Tuple[str, ...] = (),
                 block_size: int = 0, t: float = 0.0):
        self.replica_id = replica_id
        self.generation = int(generation)
        self.endpoint = endpoint
        self.ready = bool(ready)
        self.queue_depth = int(queue_depth)
        self.occupancy = int(occupancy)
        self.slots = int(slots)
        self.weights_step = weights_step
        self.available_step = available_step
        self.role = str(role) if role else "both"
        self.prefix_heads = tuple(prefix_heads)
        self.block_size = int(block_size)
        self.t = float(t)

    @classmethod
    def from_payload(cls, replica_id: str, generation: int,
                     payload: str) -> Optional["ReplicaInfo"]:
        """Tolerant parse: a malformed payload (version skew, torn
        write) yields None instead of poisoning the whole listing.
        Unknown fields are ignored and missing ones default (a
        pre-disagg replica parses as ``role="both"`` with no
        ``prefix_heads``), so version-skewed fleets stay routable
        through a rollout."""
        try:
            d = json.loads(payload)
            if not isinstance(d, dict):
                return None
            heads = d.get("prefix_heads")
            if isinstance(heads, (list, tuple)):
                heads = tuple(str(h) for h in heads)
            else:
                heads = ()      # junk-typed advertisement: no hints
            return cls(replica_id, generation,
                       endpoint=str(d.get("endpoint", "")),
                       ready=bool(d.get("ready", False)),
                       queue_depth=int(d.get("queue_depth", 0) or 0),
                       occupancy=int(d.get("occupancy", 0) or 0),
                       slots=int(d.get("slots", 0) or 0),
                       weights_step=d.get("weights_step"),
                       available_step=d.get("available_step"),
                       role=str(d.get("role", "both") or "both"),
                       prefix_heads=heads,
                       block_size=int(d.get("block_size", 0) or 0),
                       t=float(d.get("t", 0.0) or 0.0))
        except (ValueError, TypeError):
            # casts included: one version-skewed replica publishing a
            # non-numeric field must not poison the whole listing
            return None

    def load(self) -> int:
        """The least-loaded dispatch key contribution from the
        replica's own heartbeat (the router adds its local in-flight
        count on top)."""
        return self.queue_depth + self.occupancy

    def __repr__(self):
        return (f"ReplicaInfo({self.replica_id!r}@{self.endpoint}, "
                f"ready={self.ready}, load={self.load()}, "
                f"weights={self.weights_step})")


def list_replicas(store, job: str) -> Dict[str, ReplicaInfo]:
    """Read the live membership for ``job`` from the store: every
    unexpired ``/paddle/serving/<job>/g<gen>/<id>`` lease, newest
    generation winning when a replica appears under several.  Raises
    whatever the store raises — the ROUTER owns the degrade-to-last-
    known policy, a bare reader should see the outage."""
    pfx = f"{SERVING_PREFIX}{job}/"
    out: Dict[str, ReplicaInfo] = {}
    for k, v in _as_store(store).list_prefix(pfx).items():
        tail = k[len(pfx):] if k.startswith(pfx) else k
        parts = [p for p in tail.split("/") if p]
        if len(parts) != 2 or not parts[0].startswith("g"):
            continue
        gen = int(parts[0][1:]) if parts[0][1:].isdigit() else 0
        info = ReplicaInfo.from_payload(parts[1], gen, v)
        if info is None:
            continue
        prev = out.get(info.replica_id)
        if prev is None or (info.generation, info.t) >= \
                (prev.generation, prev.t):
            out[info.replica_id] = info
    return out


class ReplicaRegistry:
    """A replica's membership claim: a generation-prefixed TTL lease,
    refreshed with a live status payload on a heartbeat cadence.

    The same fencing pattern as PR 9's rendezvous: the key embeds the
    restart generation, so a slow-dying replica from a prior generation
    republishes under a prefix routers scoped to the live fleet never
    merge wrongly (its TTL also expires it).  Store outages never block
    serving: a failed publish is counted (``fleet.lease.fail``) and the
    next beat retries; the worst case is the lease lapsing — membership
    loss without process loss, which the router's failover absorbs.

    The ``fleet.lease`` chaos site fires inside :meth:`publish`, so a
    deterministic spec can drop exact heartbeats and force a lease
    expiry without killing anything.
    """

    def __init__(self, store, job: str = "serve",
                 replica_id: Optional[str] = None,
                 status_fn: Optional[Callable[[], dict]] = None, *,
                 generation: Optional[int] = None, ttl: float = 6.0,
                 interval: float = 1.5,
                 payload_warn_bytes: int = 4096):
        self.store = _as_store(store)
        self.job = str(job)
        self.replica_id = replica_id or f"r{os.getpid()}"
        if generation is None:
            generation = int(os.environ.get(
                "PADDLE_RESTART_GENERATION", "0"))
        self.generation = int(generation)
        self.ttl = float(ttl)
        self.interval = float(interval)
        self.payload_warn_bytes = int(payload_warn_bytes)
        self._payload_warned = False
        self._status_fn = status_fn or (lambda: {})
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        from ..profiler import metrics as _metrics
        self._m_pub = _metrics.counter(
            "fleet.lease.published",
            "replica-registry lease heartbeats that landed")
        self._m_fail = _metrics.counter(
            "fleet.lease.fail",
            "lease heartbeats lost to store outages or chaos "
            "(serving continues; the TTL may lapse)")
        self._g_payload = _metrics.gauge(
            "fleet.registry.payload_bytes",
            "size of the last serialized lease payload (bounded: "
            "prefix_heads is capped and hash-truncated; a runaway "
            "status_fn warns once past payload_warn_bytes)")

    @property
    def key(self) -> str:
        return serving_key(self.job, self.generation, self.replica_id)

    def publish(self):
        """One lease refresh carrying the current status payload.
        Raises on store failure — the heartbeat thread owns the
        swallow-and-count policy, direct callers see the truth."""
        payload = dict(self._status_fn())
        payload.setdefault("t", time.time())
        data = json.dumps(payload)
        self._g_payload.set(len(data))
        if len(data) > self.payload_warn_bytes \
                and not self._payload_warned:
            self._payload_warned = True
            warnings.warn(
                f"replica registry payload is {len(data)} bytes "
                f"(> {self.payload_warn_bytes}); every router rereads "
                "every lease each refresh — trim the status payload",
                RuntimeWarning)
        if _chaos.active:
            _chaos.hit("fleet.lease", exc=ConnectionResetError)
        self.store.put(self.key, data, ttl=self.ttl)

    def _beat(self):
        while not self._stop.wait(self.interval):
            try:
                self.publish()
                self._m_pub.inc()
            except Exception as e:  # noqa: BLE001 — serving never blocks
                self._m_fail.inc()
                if _flight.active:
                    _flight.note("fleet", "lease_fail",
                                 replica=self.replica_id,
                                 error=f"{type(e).__name__}: {e}")

    def start(self) -> "ReplicaRegistry":
        try:
            self.publish()          # join NOW, not one interval later
            self._m_pub.inc()
        except Exception as e:      # noqa: BLE001
            self._m_fail.inc()
            warnings.warn(f"replica registry: initial lease publish "
                          f"failed ({e!r}); heartbeat will retry",
                          RuntimeWarning)
        self._thread = _conc.spawn(self._beat, name="fleet-lease")
        return self

    def deregister(self):
        """Leave cleanly: stop the heartbeat and delete the lease so
        the router drops this replica immediately instead of waiting
        out the TTL."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)
            self._thread = None
        try:
            self.store.delete(self.key)
        except Exception:   # noqa: BLE001 — the TTL expires it anyway
            pass


# ---------------------------------------------------------------------------
# weight provenance: manifest-v2 checkpoint watcher + verified swap
# ---------------------------------------------------------------------------
class WeightWatcher:
    """Watches an ``AsyncCheckpointer``-layout checkpoint directory
    (``<dir>/<step>/`` committed trees) for newly published weights.

    Verification is the PR 3 contract, applied to the serving side:
    a step is *available* only when it carries the
    ``_PADDLE_COMMITTED`` marker AND its full sha256 manifest
    re-verifies (``checkpoint.verify_checkpoint``).  Corrupt-but-marked
    trees are quarantined exactly like ``AsyncCheckpointer.restore``
    (moved to ``_quarantine/<step>``, counted ``ckpt.quarantined``) and
    never considered again; markerless trees are *invisible* — they may
    be a writer mid-commit, so they are neither loaded nor quarantined.

    ``swap_to(step)`` re-verifies (the poll->swap gap is a rot window),
    loads the tree (template-less manifest restore) and hands it to
    ``apply_fn`` — a :class:`FleetReplica` routes that to
    ``engine.swap_weights``, which applies between engine steps.  The
    previous step is remembered for the router's rollback path.
    ``auto_swap=True`` swaps without a router (single-replica
    deployments); the default only *publishes* availability and waits
    for the router's canary flow.
    """

    QUARANTINE = "_quarantine"

    def __init__(self, directory: str,
                 apply_fn: Callable[[Dict[str, Any]], None], *,
                 interval: float = 1.0, auto_swap: bool = False):
        self.directory = os.path.abspath(directory)
        self.apply_fn = apply_fn
        self.interval = float(interval)
        self.auto_swap = bool(auto_swap)
        self.current_step: Optional[int] = None
        self.previous_step: Optional[int] = None
        self.available_step: Optional[int] = None
        self._verified: set = set()
        self._quarantined: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._swap_lock = _conc.Lock(name="fleet.watcher.swap")

    # -- discovery -----------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, str(int(step)))

    def poll_once(self) -> Optional[int]:
        """Newest step that is committed AND verifies; updates
        ``available_step``.  Corrupt committed trees are quarantined
        and skipped, torn markerless trees are skipped silently."""
        from ..distributed import checkpoint as _ckpt
        try:
            names = os.listdir(self.directory)
        except OSError:
            return self.available_step
        for s in sorted((int(n) for n in names if n.isdigit()),
                        reverse=True):
            if s in self._quarantined:
                continue
            d = self._step_dir(s)
            if not os.path.exists(os.path.join(d, _ckpt.COMMITTED_NAME)):
                continue      # mid-commit or torn: invisible by design
            if s not in self._verified:
                try:
                    _ckpt.verify_checkpoint(d)
                except _ckpt.CheckpointCorruptError as e:
                    self._quarantine(s, e)
                    continue
                self._verified.add(s)
            self.available_step = s
            return s
        return None

    def _quarantine(self, step: int, err: BaseException):
        """Move a corrupt committed tree aside — the same policy (and
        the same ``ckpt.quarantined`` metric) as
        ``AsyncCheckpointer.restore``, so one corruption matrix covers
        both read paths."""
        self._quarantined.add(step)
        qroot = os.path.join(self.directory, self.QUARANTINE)
        dst = os.path.join(qroot, str(step))
        try:
            os.makedirs(qroot, exist_ok=True)
            if os.path.exists(dst):
                shutil.rmtree(dst, ignore_errors=True)
            os.rename(self._step_dir(step), dst)
        except OSError:
            shutil.rmtree(self._step_dir(step), ignore_errors=True)
        from ..profiler import metrics as _metrics
        _metrics.counter("ckpt.quarantined",
                         "corrupt checkpoint steps moved aside by "
                         "restore").inc()
        if _flight.active:
            _flight.note("swap", "quarantine", step=step,
                         error=f"{err}")
        warnings.warn(f"weight watcher: checkpoint step {step} failed "
                      f"verification ({err}); quarantined under "
                      f"{qroot}", RuntimeWarning)

    # -- swap ----------------------------------------------------------
    def swap_to(self, step: int) -> int:
        """Verify + load ``step`` and apply it through ``apply_fn``.
        Raises ``CheckpointCorruptError`` (after quarantining) when the
        tree no longer verifies, or whatever the apply raised — in
        either case the old weights stay live."""
        from ..distributed import checkpoint as _ckpt
        step = int(step)
        with self._swap_lock:
            d = self._step_dir(step)
            try:
                # re-verify at swap time: the poll->swap gap is a rot
                # window, and the router's word is not provenance
                _ckpt.verify_checkpoint(d)
            except _ckpt.CheckpointCorruptError:
                self._verified.discard(step)
                if os.path.exists(os.path.join(d,
                                               _ckpt.COMMITTED_NAME)):
                    # committed-but-corrupt: move it aside like restore
                    self._quarantine(step, RuntimeError(
                        "failed re-verification at swap time"))
                # markerless: maybe a writer mid-commit — refuse but
                # never destroy it
                raise
            # template-less restore: safe here by construction — the
            # manifest just re-verified byte-for-byte and the tree is
            # host-local — so orbax's topology warning is noise;
            # silence it for this call only
            import logging
            absl_logger = logging.getLogger("absl")
            prev_level = absl_logger.level
            absl_logger.setLevel(logging.ERROR)
            try:
                tree = _ckpt.load_state(d)
            finally:
                absl_logger.setLevel(prev_level)
            self.apply_fn(tree)
            if self.current_step != step:
                self.previous_step = self.current_step
            self.current_step = step
            if _flight.active:
                _flight.note("swap", "apply", step=step)
            return step

    def maybe_swap(self) -> Optional[int]:
        """auto_swap mode: follow the newest verified step."""
        s = self.poll_once()
        if s is not None and s != self.current_step:
            try:
                return self.swap_to(s)
            except Exception as e:  # noqa: BLE001 — keep serving old
                warnings.warn(f"weight watcher: auto-swap to step {s} "
                              f"failed ({e!r}); serving previous "
                              f"weights", RuntimeWarning)
        return None

    # -- lifecycle -----------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                if self.auto_swap:
                    self.maybe_swap()
                else:
                    self.poll_once()
            except Exception as e:  # noqa: BLE001 — watcher must survive
                warnings.warn(f"weight watcher poll failed ({e!r})",
                              RuntimeWarning)

    def start(self) -> "WeightWatcher":
        self._thread = _conc.spawn(self._loop, name="fleet-watcher")
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)
            self._thread = None


# ---------------------------------------------------------------------------
# one fleet member: engine(s) + HTTP server + lease + watcher
# ---------------------------------------------------------------------------
class FleetReplica:
    """One serving fleet member, assembled: engine(s) + HTTP frontend +
    TTL-lease registry heartbeat + weight watcher, with the ordered
    graceful drain the single-process server could not give you —
    ``shutdown()`` stops accepting, finishes in-flight SSE streams,
    deregisters the lease, THEN closes the engines."""

    def __init__(self, engine=None, generation_engine=None, *, store,
                 job: str = "serve", replica_id: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 watch_dir: Optional[str] = None,
                 watch_interval: float = 1.0, auto_swap: bool = False,
                 load_on_start: bool = True, lease_ttl: float = 6.0,
                 heartbeat_interval: float = 1.5,
                 generation: Optional[int] = None,
                 role: str = "both", prefix_heads_k: int = 8,
                 verbose: bool = False):
        from .engine import GenerationEngine
        from .server import ServingServer
        if generation_engine is None and isinstance(engine,
                                                    GenerationEngine):
            engine, generation_engine = None, engine
        if engine is None and generation_engine is None:
            raise ValueError("bind at least one engine")
        if role not in ("prefill", "decode", "both"):
            raise ValueError(f"role must be 'prefill', 'decode' or "
                             f"'both', got {role!r}")
        if role != "both" and (
                generation_engine is None
                or not hasattr(generation_engine,
                               "export_prefix_chain")):
            raise ValueError(
                f"role={role!r} needs a PagedGenerationEngine (the KV "
                "transfer layer lives on the paged block pool)")
        self.role = role
        self.prefix_heads_k = int(prefix_heads_k)
        self.engine = engine
        self.generation_engine = generation_engine
        self.store = _as_store(store)
        self.watcher = None
        if watch_dir:
            self.watcher = WeightWatcher(
                watch_dir, self._apply_tree, interval=watch_interval,
                auto_swap=auto_swap)
            if load_on_start:
                s = self.watcher.poll_once()
                if s is not None:
                    # boot on the newest verified weights so every
                    # replica of a fleet serves the same step from
                    # request one
                    self.watcher.swap_to(s)
        self.registry = ReplicaRegistry(
            self.store, job, replica_id, self._status,
            generation=generation, ttl=lease_ttl,
            interval=heartbeat_interval)
        self.replica_id = self.registry.replica_id
        # decode-role replicas pull hot prefix chains from a prefill
        # peer before each local prefill (best-effort: any transfer
        # failure simply re-prefills locally)
        self._disagg = None
        if role == "decode":
            from .disagg import DisaggClient
            self._disagg = DisaggClient(
                self.store, job, generation_engine,
                replica_id=self.replica_id)
        self.server = ServingServer(
            engine, generation_engine=generation_engine, host=host,
            port=port, registry=self.registry, fleet_admin=self,
            role=role,
            kv_prefetch=(self._disagg.ensure_chain
                         if self._disagg is not None else None),
            verbose=verbose)
        self.endpoint = f"{self.server.host}:{self.server.port}"
        self._started = False

    # -- status --------------------------------------------------------
    def _engines(self):
        return [e for e in (self.engine, self.generation_engine)
                if e is not None]

    @property
    def ready(self) -> bool:
        return all(getattr(e, "ready", True) for e in self._engines())

    def _status(self) -> dict:
        d = {
            "endpoint": self.endpoint,
            "ready": self.ready,
            "role": self.role,
            "queue_depth": sum(e._admission.depth
                               for e in self._engines()),
            "occupancy": sum(getattr(e, "occupancy", 0)
                             for e in self._engines()),
            "slots": (self.generation_engine.slots
                      if self.generation_engine is not None else
                      self.engine.config.max_batch_size),
        }
        pc = getattr(self.generation_engine, "prefix_cache", None)
        if pc is not None and self.prefix_heads_k > 0:
            # bounded advertisement: K MRU chain heads, 16-hex each —
            # the router's prefix-locality dispatch signal
            d["prefix_heads"] = pc.hot_heads(self.prefix_heads_k)
            d["block_size"] = self.generation_engine.pool.block_size
        if self.watcher is not None:
            d["weights_step"] = self.watcher.current_step
            d["available_step"] = self.watcher.available_step
        return d

    def health_fields(self) -> dict:
        """Extra ``/healthz`` fields (the router's canary controller
        reads weight provenance here when it probes)."""
        d = {"replica_id": self.replica_id}
        if self.watcher is not None:
            d["weights_step"] = self.watcher.current_step
            d["available_step"] = self.watcher.available_step
        return d

    # -- weight swap plumbing ------------------------------------------
    def _apply_tree(self, tree: Dict[str, Any]):
        """Route a restored checkpoint tree into every bound engine's
        between-steps swap.  Accepts ``save_layer``/``Model.fit`` trees
        ({"params": ..., "buffers": ...}, extra keys like opt/rng
        ignored) or a bare flat param dict."""
        params = tree.get("params", tree) if isinstance(tree, dict) \
            else tree
        buffers = tree.get("buffers") if isinstance(tree, dict) else None
        for e in self._engines():
            e.swap_weights(params, buffers)

    def admin_request(self, path: str, payload: dict
                      ) -> Tuple[int, dict]:
        """Fleet control plane, reached via ``POST /admin/...`` on this
        replica's HTTP server (the router drives canary / promote /
        rollback through it)."""
        from ..distributed.checkpoint import CheckpointCorruptError
        if path == "/admin/swap":
            if self.watcher is None:
                return 409, {"error": "replica has no watch_dir; "
                             "nothing to swap from"}
            step = payload.get("step")
            if step is None:
                return 400, {"error": "missing 'step'"}
            prev = self.watcher.current_step
            try:
                applied = self.watcher.swap_to(int(step))
            except CheckpointCorruptError as e:
                return 409, {"error": f"step {step} failed "
                             f"verification: {e}", "reason": "corrupt"}
            except (ValueError, RuntimeError, TimeoutError) as e:
                return 409, {"error": f"{type(e).__name__}: {e}",
                             "reason": "swap_failed"}
            return 200, {"ok": True, "step": applied, "previous": prev,
                         "replica_id": self.replica_id}
        if path == "/admin/info":
            return 200, self._status()
        if path == "/admin/kv/prefill":
            return self._admin_kv_prefill(payload)
        if path == "/admin/kv/import":
            return self._admin_kv_import(payload)
        return 404, {"error": f"no admin route {path}"}

    def _admin_kv_prefill(self, payload: dict) -> Tuple[int, dict]:
        """Pull side of the KV transfer: prefill (or reuse the cached
        chain for) ``prompt_ids`` and return the serialized chain blob
        plus the first sampled token.  Decode-role replicas refuse —
        their pool is decode inventory, not prefill scratch."""
        import base64
        from .admission import EngineClosed, RequestRejected
        if self.role == "decode":
            return 409, {"error": "decode-role replica does not "
                         "prefill for peers", "reason": "wrong_role"}
        eng = self.generation_engine
        if eng is None or not hasattr(eng, "export_prefix_chain"):
            return 409, {"error": "no paged generation engine bound",
                         "reason": "no_paged_engine"}
        prompt = payload.get("prompt_ids")
        if not isinstance(prompt, (list, tuple)) or not prompt:
            return 400, {"error": "missing 'prompt_ids'"}
        first = None
        try:
            blob = eng.export_prefix_chain(prompt)
            if blob is None:
                # cold chain: one 1-token generate runs the chunked
                # prefill and inserts the chain into the prefix cache
                out = eng.generate(
                    prompt, max_new_tokens=1,
                    timeout=float(payload.get("timeout", 120.0)))
                first = int(out[0]) if len(out) else None
                blob = eng.export_prefix_chain(prompt)
        except EngineClosed as e:
            return 503, {"error": str(e), "reason": "closed"}
        except RequestRejected as e:
            return 429, {"error": str(e),
                         "reason": getattr(e, "reason", "rejected")}
        if blob is None:
            # prefix cache disabled or the chain was evicted under cap
            # pressure between prefill and export: nothing to ship
            return 409, {"error": "no cached chain to export "
                         "(prefix cache disabled or evicted)",
                         "reason": "no_chain"}
        out = {"ok": True, "bytes": len(blob),
               "blob": base64.b64encode(blob).decode("ascii"),
               "replica_id": self.replica_id}
        if first is not None:
            out["first_token"] = first
        return 200, out

    def _admin_kv_import(self, payload: dict) -> Tuple[int, dict]:
        """Push side of the KV transfer: verify a shipped chain blob
        and adopt it into this replica's block pool + prefix cache."""
        import base64
        import binascii
        from ..generation import BlockPoolExhausted, KVTransferCorrupt
        from .admission import EngineClosed
        eng = self.generation_engine
        if self.role == "prefill":
            return 409, {"error": "prefill-role replica does not "
                         "adopt chains", "reason": "wrong_role"}
        if eng is None or not hasattr(eng, "import_prefix_chain"):
            return 409, {"error": "no paged generation engine bound",
                         "reason": "no_paged_engine"}
        b64 = payload.get("blob")
        if not isinstance(b64, str) or not b64:
            return 400, {"error": "missing 'blob'"}
        try:
            blob = base64.b64decode(b64, validate=True)
        except (binascii.Error, ValueError):
            return 400, {"error": "blob is not valid base64"}
        try:
            covered = eng.import_prefix_chain(blob)
        except KVTransferCorrupt as e:
            return 409, {"error": str(e), "reason": "corrupt"}
        except BlockPoolExhausted as e:
            return 429, {"error": str(e), "reason": "kv_blocks"}
        except EngineClosed as e:
            return 503, {"error": str(e), "reason": "closed"}
        return 200, {"ok": True, "covered": covered,
                     "replica_id": self.replica_id}

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FleetReplica":
        self.server.start()
        if self.watcher is not None:
            self.watcher.start()
        self.registry.start()
        self._started = True
        return self

    def shutdown(self, drain_s: float = 30.0):
        """The ordered drain: stop accepting, finish in-flight streams,
        deregister the lease (``ServingServer.stop`` owns those three,
        in that order), THEN close the engines — an active SSE handler
        can never race a closing engine."""
        if self.watcher is not None:
            self.watcher.stop()
        self.server.stop(drain_s=drain_s)
        for e in self._engines():
            e.close()
        self._started = False

    def run(self, poll: float = 0.5):
        """Serve until SIGTERM/SIGINT, then drain (subprocess entry
        point for launcher-spawned replicas)."""
        stop = threading.Event()

        def _sig(_s, _f):
            stop.set()

        signal.signal(signal.SIGTERM, _sig)
        signal.signal(signal.SIGINT, _sig)
        if not self._started:
            self.start()
        while not stop.wait(poll):
            pass
        self.shutdown()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()


# ---------------------------------------------------------------------------
# failover classification (rides utils/resilience.retry)
# ---------------------------------------------------------------------------
FAILOVER_ERRNOS = {errno.ECONNREFUSED, errno.ECONNRESET, errno.EPIPE,
                   errno.ETIMEDOUT, errno.ECONNABORTED,
                   errno.EHOSTUNREACH, errno.ENETUNREACH}


class NoReplicaAvailable(RuntimeError):
    """The fleet has no dispatchable (ready, non-denylisted) replica;
    the router answers 503 + Retry-After."""


class _ReplicaUnavailable(ConnectionError):
    """A replica answered 503: it is closing/draining — failover-able
    by classification (another replica can absorb the request)."""


class _ClientGone(RuntimeError):
    """The ROUTER's client hung up mid-response: abort, never retry."""


def failover_classify(exc: BaseException) -> bool:
    """True when a dispatch failure is a *transport* failure another
    replica can absorb — connection refused/reset/aborted, broken
    pipe, timeouts, a dead upstream mid-response, or a replica's own
    503 (draining).  False for everything application-level: the
    request reached a live engine and the answer (400/404/429/500/504)
    is the answer — repeating it on another replica repeats the
    outcome and doubles the damage of non-idempotent mistakes."""
    if isinstance(exc, _ReplicaUnavailable):
        return True
    if isinstance(exc, (ConnectionRefusedError, ConnectionResetError,
                        ConnectionAbortedError, BrokenPipeError,
                        socket.timeout, TimeoutError,
                        http.client.BadStatusLine,
                        http.client.IncompleteRead,
                        http.client.CannotSendRequest)):
        # IncompleteRead/BadStatusLine: the replica died mid-response
        # (a SIGKILL lands as either, depending on where the kernel cut
        # the stream) — same failover as a reset
        return True
    if isinstance(exc, OSError):
        return exc.errno in FAILOVER_ERRNOS
    return False


# ---------------------------------------------------------------------------
# the router frontend
# ---------------------------------------------------------------------------
class FleetRouter:
    """HTTP router over a replica fleet discovered from the registry.

    See the module docstring for the full semantics.  Knobs:

    refresh_interval   registry re-list cadence (s)
    probe_interval     per-replica /healthz probe cadence (s)
    probe_failures     consecutive probe failures before a replica is
                       drained + denylisted (probe success readmits)
    max_inflight       router-wide concurrent-request bound; beyond it
                       requests shed with 429 + Retry-After
    retry_tries        dispatch attempts per request (each on a
                       different replica while any remain untried)
    canary_requests    completed canary-replica requests the error-rate
                       window needs before promoting new weights
    canary_max_errors  errors tolerated inside that window (beyond ->
                       rollback + step blacklisted)
    canary_timeout_s   window wall-clock bound: expiry promotes on a
                       clean record, rolls back on any error
    manage_swaps       False turns the canary controller off (the
                       router only routes; swaps are driven externally)
    """

    def __init__(self, store, job: str = "serve", *,
                 host: str = "127.0.0.1", port: int = 0,
                 refresh_interval: float = 0.5,
                 probe_interval: float = 0.5,
                 probe_timeout: float = 2.0, probe_failures: int = 3,
                 max_inflight: int = 64,
                 max_body_bytes: int = 64 << 20,
                 request_timeout: float = 120.0, retry_tries: int = 4,
                 retry_base_delay: float = 0.05,
                 canary_requests: int = 4, canary_max_errors: int = 0,
                 canary_timeout_s: float = 60.0,
                 admin_timeout: float = 60.0,
                 manage_swaps: bool = True, verbose: bool = False):
        from http.server import BaseHTTPRequestHandler, \
            ThreadingHTTPServer
        self.store = _as_store(store)
        self.job = str(job)
        self.refresh_interval = float(refresh_interval)
        self.probe_interval = float(probe_interval)
        self.probe_timeout = float(probe_timeout)
        self.probe_failures = max(1, int(probe_failures))
        self.max_inflight = int(max_inflight)
        self.max_body_bytes = int(max_body_bytes)
        self.request_timeout = float(request_timeout)
        self.retry_tries = max(1, int(retry_tries))
        self.retry_base_delay = float(retry_base_delay)
        self.canary_requests = max(1, int(canary_requests))
        self.canary_max_errors = int(canary_max_errors)
        self.canary_timeout_s = float(canary_timeout_s)
        self.admin_timeout = float(admin_timeout)
        self.manage_swaps = bool(manage_swaps)
        self.verbose = bool(verbose)

        self._lock = _conc.Lock(name="fleet.router")
        from .admission import DrainRateEstimator
        # Retry-After on router sheds comes from the observed dispatch
        # drain rate (clamped [1, 30]s), not a constant
        self._drain = DrainRateEstimator()
        self._replicas: Dict[str, ReplicaInfo] = {}
        self._deny: Dict[str, float] = {}
        self._probe_fail: Dict[str, int] = {}
        self._inflight = 0
        self._inflight_by: Dict[str, int] = {}
        self._registry_degraded = False
        self._canary: Optional[dict] = None
        self._bad_steps: set = set()
        self._current_step: Optional[int] = None
        self._stop = threading.Event()
        self._control: Optional[threading.Thread] = None

        from ..profiler import metrics as _metrics
        self._m_dispatched = _metrics.counter(
            "fleet.router.dispatched",
            "requests the router relayed to a replica (any status)")
        self._m_shed = _metrics.counter(
            "fleet.router.shed",
            "requests shed with 429 at the router's in-flight bound "
            "(typed backpressure, never an unbounded queue)")
        self._m_no_replica = _metrics.counter(
            "fleet.router.no_replica",
            "requests answered 503: no dispatchable replica")
        self._m_gave_up = _metrics.counter(
            "fleet.router.gave_up",
            "requests whose transport retries exhausted (502)")
        self._m_denylisted = _metrics.counter(
            "fleet.router.denylisted",
            "replicas drained after consecutive probe failures")
        self._m_readmitted = _metrics.counter(
            "fleet.router.readmitted",
            "denylisted replicas readmitted after a probe recovered")
        self._m_degraded = _metrics.counter(
            "fleet.registry.degraded",
            "registry refreshes that fell back to last-known "
            "membership (store outage)")
        self._g_replicas = _metrics.gauge(
            "fleet.router.replicas", "replicas in the router's view")
        self._g_inflight = _metrics.gauge(
            "fleet.router.inflight", "requests the router is relaying")
        _metrics.counter("fleet.router.retry",
                         "dispatch attempts retried on another replica "
                         "after a failover-able transport failure")

        # the per-request failover loop: each attempt picks a replica
        # not yet tried, transport failures (per failover_classify)
        # back off exponentially and go again, application responses
        # return straight through
        self._with_failover = _resilience.retry(
            retry_on=(OSError, http.client.HTTPException),
            classify=failover_classify, max_tries=self.retry_tries,
            base_delay=self.retry_base_delay, max_delay=0.5,
            deadline=self.request_timeout,
            metric="fleet.router.retry")

        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # pragma: no cover
                if router.verbose:
                    super().log_message(fmt, *args)

            def do_POST(self):      # noqa: N802
                router.handle_post(self)

        Handler.do_GET = _router_do_get(router)
        self._handler_cls = Handler

        class Srv(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                import sys as _sys
                exc = _sys.exc_info()[1]
                if isinstance(exc, (ConnectionResetError,
                                    BrokenPipeError)):
                    return  # clients hanging up is traffic, not error
                super().handle_error(request, client_address)

        self._httpd = Srv((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    # -- membership ----------------------------------------------------
    def _refresh(self):
        try:
            fresh = list_replicas(self.store, self.job)
        except Exception:   # noqa: BLE001 — degrade, never block routing
            self._m_degraded.inc()
            with self._lock:
                self._registry_degraded = True
            return
        with self._lock:
            known = set(self._replicas)
            joined = sorted(set(fresh) - known)
            left = sorted(known - set(fresh))
            self._replicas = fresh
            self._registry_degraded = False
            for rid in joined:
                # a (re)joining replica starts with a clean slate
                self._deny.pop(rid, None)
                self._probe_fail.pop(rid, None)
            for rid in left:
                self._deny.pop(rid, None)
                self._probe_fail.pop(rid, None)
            self._g_replicas.set(len(fresh))
        for rid in joined:
            if _flight.active:
                _flight.note("replica", "join", replica=rid,
                             endpoint=fresh[rid].endpoint)
        for rid in left:
            if _flight.active:
                _flight.note("replica", "leave", replica=rid,
                             reason="lease")

    def _probe(self):
        with self._lock:
            targets = [(rid, i.endpoint)
                       for rid, i in self._replicas.items()]
        for rid, endpoint in targets:
            if not endpoint:
                continue
            ok = False
            try:
                h, p = endpoint.rsplit(":", 1)
                conn = http.client.HTTPConnection(
                    h, int(p), timeout=self.probe_timeout)
                try:
                    conn.request("GET", "/healthz")
                    resp = conn.getresponse()
                    resp.read()
                    ok = resp.status == 200
                finally:
                    conn.close()
            except Exception:   # noqa: BLE001 — a failed probe is data
                ok = False
            with self._lock:
                if ok:
                    self._probe_fail[rid] = 0
                    if rid in self._deny:
                        del self._deny[rid]
                        self._m_readmitted.inc()
                        if _flight.active:
                            _flight.note("replica", "readmit",
                                         replica=rid)
                else:
                    n = self._probe_fail.get(rid, 0) + 1
                    self._probe_fail[rid] = n
                    if n >= self.probe_failures and \
                            rid not in self._deny:
                        self._deny[rid] = time.time()
                        self._m_denylisted.inc()
                        if _flight.active:
                            _flight.note("replica", "deny",
                                         replica=rid, probes=n)

    def _dispatchable(self, exclude=()) -> List[ReplicaInfo]:
        """Ready, non-denylisted replicas, least-loaded first (router
        in-flight + the replica's own published queue/occupancy).
        Prefill-role replicas never take client traffic — decode-role
        peers reach them through ``/admin/kv/prefill``."""
        with self._lock:
            infos = list(self._replicas.values())
            deny = set(self._deny)
            mine = dict(self._inflight_by)
        out = []
        for i in infos:
            if i.replica_id in deny or i.replica_id in exclude:
                continue
            if not i.ready or not i.endpoint:
                continue
            if i.role == "prefill":
                continue
            out.append((mine.get(i.replica_id, 0) + i.load(),
                        i.replica_id, i))
        out.sort(key=lambda x: (x[0], x[1]))
        return [x[2] for x in out]

    def _prefix_score(self, info: ReplicaInfo, prompt,
                      dcache: dict) -> int:
        """Longest prefix of ``prompt`` (in tokens) whose chain digest
        the replica advertises in its heartbeat ``prefix_heads`` — the
        dispatch signal that lands shared-prompt traffic where the KV
        blocks already live.  Purely a hint: truncated-hash collisions
        or stale advertisements cost one prefix-cache miss, never
        correctness.  ``dcache`` memoizes the prompt's digest walk per
        block size across the candidates of one dispatch."""
        if prompt is None or not info.prefix_heads \
                or info.block_size < 1:
            return 0
        digs = dcache.get(info.block_size)
        if digs is None:
            from ..generation.kv_wire import chain_digests
            digs = chain_digests(prompt, info.block_size)
            dcache[info.block_size] = digs
        heads = set(info.prefix_heads)
        best = 0
        for n, d in digs:
            if n > best and d in heads:
                best = n
        return best

    def _pick(self, tried: set, prompt=None,
              dcache: Optional[dict] = None) -> ReplicaInfo:
        cands = self._dispatchable(exclude=tried)
        if not cands:
            # everything tried already: allow another pass (backoff
            # happened between attempts; a restarted replica may be
            # back) before giving up entirely
            cands = self._dispatchable()
        if not cands:
            raise NoReplicaAvailable(
                f"no dispatchable replica for job {self.job!r} "
                f"({len(self._replicas)} known, "
                f"{len(self._deny)} denylisted)")
        if prompt is not None and dcache is not None \
                and len(cands) > 1:
            # longest-published-prefix first; the least-loaded order
            # of _dispatchable breaks ties (score 0 everywhere
            # degrades to exactly the pre-disagg least-loaded pick)
            best_i, best_s = 0, self._prefix_score(cands[0], prompt,
                                                   dcache)
            for i in range(1, len(cands)):
                s = self._prefix_score(cands[i], prompt, dcache)
                if s > best_s:
                    best_i, best_s = i, s
            if best_s > 0:
                from ..profiler import metrics as _metrics
                _metrics.counter(
                    "fleet.router.prefix_routed",
                    "dispatches steered by a published prefix-chain "
                    "head match (vs pure least-loaded)").inc()
                return cands[best_i]
        return cands[0]

    # -- canary / promote / rollback -----------------------------------
    def _admin_swap(self, info: ReplicaInfo, step: int) -> dict:
        h, p = info.endpoint.rsplit(":", 1)
        conn = http.client.HTTPConnection(h, int(p),
                                          timeout=self.admin_timeout)
        try:
            body = json.dumps({"step": int(step)}).encode()
            conn.request("POST", "/admin/swap", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
        finally:
            conn.close()
        try:
            doc = json.loads(data.decode() or "{}")
        except ValueError:
            doc = {}
        doc["_status"] = resp.status
        return doc

    def _canary_note(self, rid: str, ok: bool):
        """Count one completed request against the open canary window
        (only traffic that landed on the canary replica counts)."""
        with self._lock:
            c = self._canary
            if c is not None and c["replica"] == rid:
                c["ok" if ok else "err"] += 1

    def _canary_tick(self):
        cands = self._dispatchable()
        if not cands:
            return
        with self._lock:
            c = self._canary
        if c is None:
            steps = [i.available_step for i in cands
                     if i.available_step is not None]
            if not steps:
                return
            target = max(int(s) for s in steps)
            if target in self._bad_steps:
                return
            if all(i.weights_step == target for i in cands):
                with self._lock:
                    self._current_step = target   # already converged
                return
            if target == self._current_step:
                # promoted already: heal stragglers/late joiners
                # directly, no second canary for a proven step
                for i in cands:
                    if i.weights_step != target and \
                            (i.available_step or 0) >= target:
                        self._swap_or_note(i, target, phase="converge")
                return
            canary = next((i for i in cands
                           if (i.available_step or 0) >= target
                           and i.weights_step != target), None)
            if canary is None:
                return
            doc = self._swap_or_note(canary, target, phase="canary")
            if doc is None:
                return
            with self._lock:
                self._canary = {
                    "step": target, "replica": canary.replica_id,
                    "prev": doc.get("previous"), "ok": 0, "err": 0,
                    "t0": time.monotonic()}
            if _flight.active:
                _flight.note("swap", "canary", step=target,
                             replica=canary.replica_id)
            return
        # a canary window is open: judge it
        if c["replica"] not in {i.replica_id for i in cands}:
            # the canary vanished mid-window (killed, denylisted, lease
            # expired): no verdict either way — close the window without
            # blacklisting so a surviving replica can retry the step
            self._abort_canary(c, reason="canary_lost", rpc=False)
            return
        done = (c["ok"] + c["err"]) >= self.canary_requests
        expired = time.monotonic() - c["t0"] > self.canary_timeout_s
        if c["err"] > self.canary_max_errors or (expired and c["err"]):
            self._rollback(c)
        elif done or (expired and c["ok"]):
            self._promote(c, cands)
        elif expired:
            # window expired with ZERO samples: no evidence to promote
            # on — return the canary to the previous step and retry the
            # rollout when there is traffic to judge by
            self._abort_canary(c, reason="no_traffic")

    def _swap_or_note(self, info: ReplicaInfo, step: int, *,
                      phase: str) -> Optional[dict]:
        """One /admin/swap RPC with the failure policy attached: a
        corrupt-verdict (409) blacklists the step, transport failures
        leave it retryable next tick."""
        try:
            doc = self._admin_swap(info, step)
        except Exception as e:  # noqa: BLE001 — control plane is retried
            if _flight.active:
                _flight.note("swap", "abort", step=step, phase=phase,
                             replica=info.replica_id,
                             error=f"{type(e).__name__}: {e}")
            return None
        if doc.get("_status") != 200:
            if doc.get("reason") == "corrupt":
                self._bad_steps.add(step)
            if _flight.active:
                _flight.note("swap", "abort", step=step, phase=phase,
                             replica=info.replica_id,
                             error=doc.get("error"))
            return None
        return doc

    def _promote(self, c: dict, cands: List[ReplicaInfo]):
        step = c["step"]
        promoted = 0
        for i in cands:
            if i.replica_id == c["replica"] or i.weights_step == step:
                continue
            if self._swap_or_note(i, step, phase="promote") is not None:
                promoted += 1
        with self._lock:
            self._canary = None
            self._current_step = step
        from ..profiler import metrics as _metrics
        _metrics.counter(
            "fleet.swap.promoted",
            "fleet-wide weight promotions after a clean canary "
            "window").inc()
        if _flight.active:
            _flight.note("swap", "promote", step=step,
                         replicas=promoted, canary=c["replica"],
                         window_ok=c["ok"], window_err=c["err"])

    def _abort_canary(self, c: dict, *, reason: str, rpc: bool = True):
        """Close a canary window that produced no verdict (no traffic,
        or the canary itself vanished).  Unlike :meth:`_rollback` the
        step is NOT blacklisted — nothing proved it bad."""
        with self._lock:
            info = next((i for i in self._replicas.values()
                         if i.replica_id == c["replica"]), None)
            self._canary = None
        if rpc and info is not None and c.get("prev") is not None:
            self._swap_or_note(info, c["prev"], phase="abort")
        if _flight.active:
            _flight.note("swap", "abort", step=c["step"],
                         phase="window", replica=c["replica"],
                         reason=reason)
        warnings.warn(
            f"fleet router: canary window on step {c['step']} closed "
            f"without a verdict ({reason}); will retry", RuntimeWarning)

    def _rollback(self, c: dict):
        step, prev = c["step"], c.get("prev")
        self._bad_steps.add(step)
        with self._lock:
            info = next((i for i in self._replicas.values()
                         if i.replica_id == c["replica"]), None)
            self._canary = None
            if prev is None:
                # a canary that had no step of its own before the swap
                # (fresh replica) reverts to the fleet's last promoted
                # step — never strand it alone on a blacklisted tree
                prev = self._current_step
        if info is not None and prev is not None:
            self._swap_or_note(info, prev, phase="rollback")
        from ..profiler import metrics as _metrics
        _metrics.counter(
            "fleet.swap.rolled_back",
            "canary windows that failed: step blacklisted, canary "
            "returned to the previous weights").inc()
        if _flight.active:
            _flight.note("swap", "rollback", step=step,
                         replica=c["replica"], to_step=prev,
                         window_ok=c["ok"], window_err=c["err"])
        warnings.warn(
            f"fleet router: canary on step {step} failed "
            f"({c['err']} error(s) in {c['ok'] + c['err']} requests); "
            f"rolled back to step {prev} and blacklisted {step}",
            RuntimeWarning)

    # -- control loop --------------------------------------------------
    def _control_loop(self):
        last_probe = 0.0
        while not self._stop.wait(self.refresh_interval):
            try:
                self._refresh()
                now = time.monotonic()
                if now - last_probe >= self.probe_interval:
                    last_probe = now
                    self._probe()
                if self.manage_swaps:
                    self._canary_tick()
            except Exception as e:  # noqa: BLE001 — router must survive
                warnings.warn(f"fleet router control loop error "
                              f"({e!r})", RuntimeWarning)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "FleetRouter":
        self._refresh()     # first view before the first request
        self._control = _conc.spawn(self._control_loop,
                                    name="fleet-router-control")
        self._thread = _conc.spawn(self._httpd.serve_forever,
                                   name="fleet-router-http")
        return self

    def stop(self):
        self._stop.set()
        if self._control is not None:
            self._control.join(timeout=5)
            self._control = None
        if self._thread is not None:
            # shutdown() blocks on serve_forever's loop — only valid
            # when the HTTP thread actually runs
            self._httpd.shutdown()
            self._thread.join(timeout=10)
            self._thread = None
        self._httpd.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- request handling ----------------------------------------------
    def health(self) -> dict:
        with self._lock:
            reps = {rid: {"endpoint": i.endpoint, "ready": i.ready,
                          "queue_depth": i.queue_depth,
                          "occupancy": i.occupancy,
                          "weights_step": i.weights_step,
                          "available_step": i.available_step,
                          "replica_role": i.role,
                          "denylisted": rid in self._deny,
                          "inflight": self._inflight_by.get(rid, 0)}
                    for rid, i in self._replicas.items()}
            canary = dict(self._canary) if self._canary else None
            return {"status": "ok", "role": "router", "job": self.job,
                    "replicas": reps,
                    "dispatchable": sum(
                        1 for rid, d in reps.items()
                        if d["ready"] and not d["denylisted"]),
                    "inflight": self._inflight,
                    "registry_degraded": self._registry_degraded,
                    "current_step": self._current_step,
                    "canary": canary,
                    "bad_steps": sorted(self._bad_steps)}

    @staticmethod
    def _send_json(h, code: int, obj, retry_after: Optional[str] = None,
                   replica: Optional[str] = None):
        data = json.dumps(obj).encode()
        try:
            h.send_response(code)
            h.send_header("Content-Type", "application/json")
            h.send_header("Content-Length", str(len(data)))
            if retry_after is not None:
                h.send_header("Retry-After", retry_after)
            if replica is not None:
                h.send_header("X-Fleet-Replica", replica)
            rid = h.headers.get("X-Request-Id")
            if rid:
                h.send_header("X-Request-Id", rid)
            h.end_headers()
            h.wfile.write(data)
        except OSError:
            pass            # client gone; nothing to salvage

    # X-Tenant / X-Priority ride every attempt of a dispatch — a
    # failover re-issue carries the same tenant identity and priority
    # class as the original, so the replacement replica admits it into
    # the same queue position class and charges the same bucket
    _FORWARD_HEADERS = ("Content-Type", "X-Request-Id", "traceparent",
                        "X-Deadline-Ms", "X-Tenant", "X-Priority")

    def handle_post(self, h):
        if h.path not in ("/v1/infer", "/infer", "/v1/generate",
                          "/generate"):
            self._send_json(h, 404, {"error": f"no route {h.path}"})
            return
        length = int(h.headers.get("Content-Length") or 0)
        if self.max_body_bytes and length > self.max_body_bytes:
            # the oversized body is deliberately left unread (buffering
            # it would defeat the cap) — drop the keep-alive connection
            # so the unread bytes can't be parsed as the next request
            h.close_connection = True
            self._send_json(h, 413, {
                "error": f"request body {length} bytes exceeds the "
                f"router cap {self.max_body_bytes}",
                "reason": "body_too_large"})
            return
        body = h.rfile.read(length)
        stream = False
        prompt = None
        if h.path in ("/v1/generate", "/generate"):
            try:
                doc = json.loads(body.decode() or "{}")
                stream = bool(doc.get("stream", False))
                p = doc.get("prompt_ids", doc.get("prompt"))
                if isinstance(p, list) and p:
                    prompt = p      # the prefix-routing signal
            except Exception:   # noqa: BLE001 — replica answers the 400
                stream = False
        # router admission: shed with a TYPED 429 + Retry-After before
        # queueing unboundedly — the same honesty contract as engine
        # admission, one tier up
        with self._lock:
            if self._inflight >= self.max_inflight:
                self._m_shed.inc()
                shed = True
            else:
                self._inflight += 1
                self._g_inflight.set(self._inflight)
                shed = False
        if shed:
            self._send_json(h, 429, {
                "error": f"router at max_inflight="
                f"{self.max_inflight}; retry with backoff",
                "reason": "router_overload"},
                retry_after=str(self._drain.retry_after_s(
                    self._inflight)))
            return
        try:
            self._dispatch(h, h.path, body, stream, prompt)
        finally:
            with self._lock:
                self._inflight -= 1
                self._g_inflight.set(self._inflight)

    def _dispatch(self, h, path: str, body: bytes, stream: bool,
                  prompt=None):
        headers = {k: h.headers[k] for k in self._FORWARD_HEADERS
                   if h.headers.get(k) is not None}
        headers["Content-Length"] = str(len(body))
        tried: set = set()
        dcache: dict = {}   # prompt digest walk, shared per dispatch
        # SSE splice cursor: token events the client already has; a
        # failed-over stream re-issues the request (seed-deterministic)
        # and skips past them
        state = {"delivered": 0, "headers_sent": False,
                 "terminal": False}
        last = {"rid": None}

        def attempt():
            info = self._pick(tried, prompt, dcache)
            rid = info.replica_id
            tried.add(rid)
            last["rid"] = rid
            with self._lock:
                self._inflight_by[rid] = \
                    self._inflight_by.get(rid, 0) + 1
            try:
                if stream:
                    return rid, self._forward_stream(h, info, path,
                                                     body, headers,
                                                     state)
                return rid, self._forward_plain(h, info, path, body,
                                                headers)
            finally:
                with self._lock:
                    n = self._inflight_by.get(rid, 1) - 1
                    if n <= 0:
                        self._inflight_by.pop(rid, None)
                    else:
                        self._inflight_by[rid] = n

        try:
            rid, status = self._with_failover(attempt)()
        except NoReplicaAvailable as e:
            self._m_no_replica.inc()
            if stream and state["headers_sent"]:
                # the 200 + chunked headers already went out: a second
                # status line would corrupt the stream — the terminal
                # event is the only honest channel left
                try:
                    self._sse_emit(h, json.dumps(
                        {"error": str(e), "reason": "no_replica"}))
                    self._sse_end(h)
                except OSError:
                    pass
            else:
                self._send_json(h, 503, {"error": str(e),
                                         "reason": "no_replica"},
                                retry_after=str(
                                    self._drain.retry_after_s(
                                        self._inflight)))
            return
        except _ClientGone:
            return
        except Exception as e:  # noqa: BLE001 — transport retries spent
            self._m_gave_up.inc()
            self._canary_note(last["rid"], ok=False)
            if stream and state["headers_sent"]:
                # the 200 already went out: surface as a terminal event
                try:
                    self._sse_emit(h, json.dumps(
                        {"error": f"{type(e).__name__}: {e}",
                         "reason": "fleet_exhausted"}))
                    self._sse_end(h)
                except OSError:
                    pass
            else:
                self._send_json(h, 502, {
                    "error": f"every dispatch attempt failed "
                    f"(last: {type(e).__name__}: {e})",
                    "reason": "fleet_exhausted"},
                    retry_after=str(self._drain.retry_after_s(
                        self._inflight)))
            return
        self._m_dispatched.inc()
        self._drain.note()
        # canary accounting: 2xx is a clean sample, a 5xx on the NEW
        # weights is exactly what the window exists to catch (4xx is
        # the client's fault, not the weights')
        if status is not None:
            self._canary_note(rid, ok=status < 500)

    # -- plain (non-streaming) forward ---------------------------------
    def _forward_plain(self, h, info: ReplicaInfo, path: str,
                       body: bytes, headers: dict) -> int:
        if _chaos.active:
            _chaos.hit("router.dispatch", exc=ConnectionResetError)
        host, port = info.endpoint.rsplit(":", 1)
        conn = http.client.HTTPConnection(
            host, int(port), timeout=self.request_timeout)
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            status = resp.status
            ctype = resp.getheader("Content-Type") or "application/json"
        finally:
            conn.close()
        if status == 503:
            # the replica is draining/closed — failover-able by
            # classification; any OTHER application status is final
            raise _ReplicaUnavailable(
                f"replica {info.replica_id} answered 503")
        try:
            h.send_response(status)
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(data)))
            h.send_header("X-Fleet-Replica", info.replica_id)
            h.end_headers()
            h.wfile.write(data)
        except OSError as e:
            raise _ClientGone() from e
        return status

    # -- SSE (streaming) forward with mid-stream failover --------------
    def _sse_headers(self, h, info: ReplicaInfo):
        h.send_response(200)
        h.send_header("Content-Type", "text/event-stream")
        h.send_header("Cache-Control", "no-cache")
        h.send_header("Transfer-Encoding", "chunked")
        h.send_header("X-Fleet-Replica", info.replica_id)
        h.end_headers()

    @staticmethod
    def _sse_emit(h, payload: str):
        data = f"data: {payload}\n\n".encode()
        h.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        h.wfile.flush()

    @staticmethod
    def _sse_end(h):
        h.wfile.write(b"0\r\n\r\n")

    def _forward_stream(self, h, info: ReplicaInfo, path: str,
                        body: bytes, headers: dict,
                        state: dict) -> Optional[int]:
        if _chaos.active:
            _chaos.hit("router.dispatch", exc=ConnectionResetError)
        host, port = info.endpoint.rsplit(":", 1)
        conn = http.client.HTTPConnection(
            host, int(port), timeout=self.request_timeout)
        try:
            conn.request("POST", path, body=body, headers=headers)
            resp = conn.getresponse()
            if resp.status == 503:
                resp.read()
                raise _ReplicaUnavailable(
                    f"replica {info.replica_id} answered 503")
            if resp.status != 200:
                data = resp.read()
                if not state["headers_sent"]:
                    # pre-stream rejection: relay verbatim
                    try:
                        h.send_response(resp.status)
                        ctype = resp.getheader("Content-Type") \
                            or "application/json"
                        h.send_header("Content-Type", ctype)
                        h.send_header("Content-Length", str(len(data)))
                        h.send_header("X-Fleet-Replica",
                                      info.replica_id)
                        h.end_headers()
                        h.wfile.write(data)
                    except OSError as e:
                        raise _ClientGone() from e
                    return resp.status
                # mid-retry rejection with the 200 long gone: terminal
                # error event is the only honest channel left
                try:
                    self._sse_emit(h, json.dumps(
                        {"error": f"retry got HTTP {resp.status}",
                         "status": resp.status}))
                    self._sse_end(h)
                except OSError as e:
                    raise _ClientGone() from e
                state["terminal"] = True
                return 500
            if not state["headers_sent"]:
                try:
                    self._sse_headers(h, info)
                except OSError as e:
                    raise _ClientGone() from e
                state["headers_sent"] = True
            status = self._relay_sse(h, resp, state)
            if not state["terminal"]:
                # upstream closed cleanly but never sent done/error:
                # treat as a dead replica mid-stream
                raise ConnectionResetError(
                    f"replica {info.replica_id} ended the stream "
                    "without a terminal event")
            try:
                self._sse_end(h)
            except OSError:
                pass
            return status
        finally:
            conn.close()

    def _relay_sse(self, h, resp, state: dict) -> int:
        """Relay SSE events; skip token events the client already has
        (the failover splice), stop at the terminal event.  Returns the
        effective status (200, or 500 when the terminal event was an
        error)."""
        status = 200
        buf: List[bytes] = []
        while True:
            line = resp.readline()
            if not line:
                return status          # EOF — caller decides
            if line.strip():
                buf.append(line)
                continue
            event = b"".join(buf)
            buf = []
            if not event.strip():
                continue
            payload = event.split(b"data:", 1)
            text = payload[1].strip().decode() if len(payload) == 2 \
                else ""
            try:
                doc = json.loads(text) if text else {}
            except ValueError:
                doc = {}
            if "token" in doc:
                idx = int(doc.get("index", state["delivered"]))
                if idx < state["delivered"]:
                    continue           # splice: client already has it
                try:
                    self._sse_emit(h, text)
                except OSError as e:
                    raise _ClientGone() from e
                state["delivered"] += 1
                continue
            # terminal: done or error
            state["terminal"] = True
            if "error" in doc:
                status = 500
            try:
                self._sse_emit(h, text)
            except OSError as e:
                raise _ClientGone() from e
            return status


def _router_do_get(router):
    """The router's GET handler, bound late so the metrics import is
    module-correct regardless of how the Handler class was nested."""
    def do_GET(self):               # noqa: N802
        if self.path == "/healthz":
            router._send_json(self, 200, router.health())
        elif self.path == "/metrics":
            from ..profiler import metrics as _m
            data = _m.prometheus_text().encode()
            try:
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)
            except OSError:
                pass
        else:
            router._send_json(self, 404, {
                "error": f"no route {self.path}; the router serves "
                "/v1/infer, /v1/generate, /healthz, /metrics"})
    return do_GET

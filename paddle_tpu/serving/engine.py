"""InferenceEngine: dynamic batching over a cloned-predictor pool.

The synchronous ``Predictor`` answers one request per dispatch; under
concurrent traffic every caller pays a full device round-trip and every
novel shape a full XLA compile.  The engine turns a saved artifact into
a servable endpoint:

- callers ``submit()`` (future) or ``infer()`` (blocking); requests pass
  admission control (bounded queue, per-request deadlines, explicit
  overload rejection — ``admission.py``);
- a batcher thread coalesces compatible requests (same non-batch dims
  and dtypes) into one padded batch per ``max_batch_size`` /
  ``batch_timeout_ms`` window — Clipper-style adaptive batching;
- batches run on a pool of ``Predictor.clone()`` workers sharing ONE
  set of device weights and one executable population;
- input shapes are bucketed (``bucketing.py``) so total compiles are
  bounded by the bucket count, not the observed-shape count;
- results fan back out per request, sliced from the batch output —
  bit-identical to an unbatched ``Predictor.run`` on the same rows.

Composition with the rest of the stack: ``serving.*`` metrics land in
the profiler registry (PR 1), the ``serve.request`` chaos site makes
fault-injected soak tests deterministic (PR 3), and program artifacts
are re-verified by the static-analysis pass bundle once at load (PR 2).
"""
from __future__ import annotations

import queue as _queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from ..profiler import flight as _flight
from ..profiler import memscope as _memscope
from ..profiler import rtrace as _rtrace
from ..profiler import tracer as _tracer
from ..utils import concurrency as _conc
from .admission import (PRIORITIES, AdmissionController, DeadlineExceeded,
                        EngineClosed, RequestRejected, TenantQuotaTable,
                        deadline_from_ms, priority_rank)
from .bucketing import BucketPolicy, ExecutableCache

__all__ = ["EngineConfig", "InferenceEngine", "RequestRejected",
           "DeadlineExceeded", "EngineClosed", "GenerationEngineConfig",
           "GenerationEngine", "GenerationStream",
           "PagedGenerationEngine"]


class EngineConfig:
    """Serving knobs (all have production-sane defaults).

    max_batch_size    rows per executed batch; also the admission cap on
                      a single request's rows
    batch_timeout_ms  how long the batcher holds an open batch waiting
                      for co-travelers before dispatching it partial
    num_workers       predictor clones executing batches concurrently
    max_queue         admission bound on waiting requests (default:
                      FLAGS_serving_queue_depth)
    deadline_ms       default per-request deadline; None = no deadline
    pad_dynamic_dims  also bucket non-batch dynamic dims (opt-in: only
                      sound for padding-invariant/masked models)
    min_batch_bucket  smallest batch bucket (e.g. 4 keeps tiny batches
                      from fragmenting the executable population)
    validate_artifact run the static-analysis verify pass over the
                      artifact's embedded program desc at load (PR 2)
    warmup            pre-populate every batch-bucket executable at
                      construction (from the AOT artifact store when
                      FLAGS_compile_cache_dir is armed — then a fresh
                      engine costs deserialization, not compiles), so
                      first-request latency equals steady state;
                      warmed-bucket count lands in /healthz.  Pass
                      ``"async"`` to warm on a background thread: the
                      engine serves immediately (cold requests compile)
                      but reports ``ready=False`` until warmup lands —
                      a fleet router treats not-ready replicas as
                      undispatchable, so traffic never lands in the
                      cold-compile window
    name              metrics prefix (default "serving"); give each
                      engine a distinct name when one process serves
                      several models, or their counters/gauges mix
    """

    def __init__(self, max_batch_size: int = 8,
                 batch_timeout_ms: float = 2.0,
                 num_workers: int = 2,
                 max_queue: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 pad_dynamic_dims: bool = False,
                 min_batch_bucket: int = 1,
                 validate_artifact: bool = True,
                 warmup: bool = False,
                 name: str = "serving"):
        if max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_ms = float(batch_timeout_ms)
        self.num_workers = int(num_workers)
        if max_queue is None:
            from ..utils import flags as _flags
            max_queue = int(_flags.get_flag("FLAGS_serving_queue_depth"))
        self.max_queue = int(max_queue)
        self.deadline_ms = deadline_ms
        self.pad_dynamic_dims = bool(pad_dynamic_dims)
        self.min_batch_bucket = int(min_batch_bucket)
        self.validate_artifact = bool(validate_artifact)
        self.warmup = warmup if warmup == "async" else bool(warmup)
        self.name = str(name)


class _Request:
    __slots__ = ("arrays", "rows", "sig", "future", "deadline",
                 "t_submit", "ctx", "t_submit_ns")

    def __init__(self, arrays, rows, sig, deadline, ctx=None):
        self.arrays = arrays
        self.rows = rows
        self.sig = sig
        self.future: Future = Future()
        self.deadline = deadline
        self.t_submit = time.monotonic()
        # request-trace context (profiler/rtrace.py) riding the request
        # across the batcher/worker thread hops; None when untraced
        self.ctx = ctx
        self.t_submit_ns = _tracer.now_ns() if ctx is not None else 0

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline


def validate_artifact(predictor, name: str = "serving"
                      ) -> Optional[object]:
    """Run the prog-san verify pass (PR 2) over the artifact's embedded
    program description, once, at load.  Program-kind artifacts saved by
    ``static.save_inference_model`` carry an op table; a malformed one
    (dangling inputs, def-after-use, broken fetches) raises here —
    at endpoint construction — instead of surfacing as a cryptic
    execution error under traffic.  Layer artifacts (pure StableHLO, no
    op table) get aval/meta consistency checks only.  Returns the
    analysis report, or None when there was nothing op-level to verify.
    """
    meta = predictor._meta
    avals = meta.get("input_avals") or []
    feed_names = predictor.get_input_names()
    if len(avals) != len(feed_names):
        raise RuntimeError(
            f"artifact metadata is inconsistent: {len(feed_names)} feed "
            f"names vs {len(avals)} input avals — was the .pdiparams "
            "file truncated or hand-edited?")
    from ..profiler import metrics as _metrics
    desc = meta.get("program_desc")
    if not desc:
        _metrics.counter(f"{name}.artifact.validated",
                         "artifacts validated at engine load").inc()
        return None
    from ..static.passes import analyze
    from ..static.program import OpDesc, Program, Variable
    prog = Program()
    for n, (shape, dt) in (desc.get("placeholders") or {}).items():
        v = Variable(n, shape, dt, program=prog)
        v.is_placeholder = True
        prog._placeholders[n] = v
        prog._vars[n] = v
    for n in desc.get("parameters", ()):
        prog.parameters[n] = None
    for n in desc.get("constants", ()):
        prog.constants[n] = None
    for n in desc.get("state_vars", ()):
        prog.state_vars[n] = None
    for row in desc.get("ops", ()):
        prog._append(OpDesc(row["type"], row["kind"], None,
                            row["inputs"], row["outputs"],
                            attrs=row.get("attrs"),
                            fwd_idx=row.get("fwd_idx")))
    feed_shapes = {n: [1 if d is None or int(d) < 0 else int(d)
                       for d in shape]
                   for n, (shape, _dt) in
                   (desc.get("placeholders") or {}).items()}
    report = analyze(prog, feed_shapes=feed_shapes,
                     fetch_names=list(desc.get("fetch_names") or ()),
                     passes=("verify",))
    report.raise_on_error()
    _metrics.counter(f"{name}.artifact.validated",
                     "artifacts validated at engine load").inc()
    return report


class InferenceEngine:
    """Dynamic-batching serving endpoint over a saved artifact.

    ``model`` is a path prefix, an ``inference.Config``, or an existing
    ``Predictor``.  See :class:`EngineConfig` for the knobs, and
    ``serving.server.ServingServer`` for the HTTP frontend.

    Contract: inputs are batch-major (dim 0 is the sample dim) and the
    model is row-independent along it — the standard inference-artifact
    shape contract, and what makes batched outputs bit-identical to
    unbatched runs of the same rows.
    """

    def __init__(self, model, config: Optional[EngineConfig] = None):
        from .. import inference as _inf
        self.config = config or EngineConfig()
        if isinstance(model, str):
            model = _inf.Config(model)
        if isinstance(model, _inf.Config):
            model = _inf.Predictor(model)
        self._base = model
        self._precision = model._config._precision.name
        self.metrics_prefix = self.config.name
        if self.config.validate_artifact:
            self.report = validate_artifact(model, name=self.config.name)
        else:
            self.report = None
        # materialize shared state BEFORE cloning so every worker holds
        # the same device weights (identity, not copies)
        if model._kind == "layer":
            model._materialize_params()
        self.input_names = model.get_input_names()
        self._policy = BucketPolicy(
            model._meta.get("input_avals") or [],
            max_batch_size=self.config.max_batch_size,
            min_batch_bucket=self.config.min_batch_bucket,
            pad_dynamic_dims=self.config.pad_dynamic_dims)
        self._cache = ExecutableCache(name=self.config.name)
        self._admission = AdmissionController(
            self.config.max_queue, max_rows=self.config.max_batch_size,
            name=self.config.name)

        from ..profiler import metrics as _metrics
        prefix = self.metrics_prefix
        self._m_latency = _metrics.histogram(
            f"{prefix}.request.latency_ms",
            "end-to-end request latency (submit -> result)")
        self._m_qwait = _metrics.histogram(
            f"{prefix}.queue_wait_ms",
            "time a request waited before entering an executed batch")
        self._m_occupancy = _metrics.histogram(
            f"{prefix}.batch.occupancy",
            "real request rows per executed batch (before padding)")
        self._m_fill = _metrics.histogram(
            f"{prefix}.batch.fill",
            "rows / bucket-size ratio of executed batches")
        self._m_pad_waste = _metrics.histogram(
            f"{prefix}.pad_waste",
            "fraction of each executed bucket that was padding")
        self._m_batches = _metrics.counter(
            f"{prefix}.batch.executed", "batches dispatched to workers")
        self._m_done = _metrics.counter(
            f"{prefix}.request.completed", "requests answered successfully")
        self._m_failed = _metrics.counter(
            f"{prefix}.request.failed", "requests completed exceptionally "
            "(model error or injected fault)")
        _metrics.gauge(f"{prefix}.workers", "predictor clones in the "
                       "pool").set(self.config.num_workers)

        # readiness: alive != dispatchable.  False while warmup is still
        # compiling buckets — /healthz reports it and the fleet router
        # treats not-ready replicas as undispatchable
        self.ready = False
        self.warmed_buckets = 0
        if self.config.warmup and self.config.warmup != "async":
            self._warmup()
            self.ready = True
        elif not self.config.warmup:
            self.ready = True        # nothing to wait for

        self._pending: deque = deque()
        # sanitizer factories (utils/concurrency.py): plain threading
        # primitives when FLAGS_lock_san=0, instrumented (order graph +
        # contention histograms) when on
        self._cond = _conc.Condition(name=f"{self.config.name}"
                                     ".engine.cond")
        # serializes metric updates issued from concurrent workers: the
        # registry's Counter.inc is deliberately lock-free (PR-1 hot
        # path), but the serving gate asserts EXACT counts, so the
        # engine's own increments must not lose races
        self._mlock = _conc.Lock(name=f"{self.config.name}"
                                 ".engine.metrics")
        self._batch_q: "_queue.Queue" = _queue.Queue(
            maxsize=max(2, 2 * self.config.num_workers))
        self._stop = False
        self._paused = False
        self._closed = False
        # quiesce bookkeeping for weight hot-swap: batches queued or
        # executing (incremented by the batcher BEFORE the queue put so
        # there is no counted-nowhere window) + whether the batcher is
        # mid-assembly of a batch
        self._inflight = 0
        self._batcher_busy = False
        self._workers: List[threading.Thread] = []
        self._predictors = [model.clone()
                            for _ in range(self.config.num_workers)]
        self._batcher = _conc.spawn(self._batcher_loop,
                                    name="serving-batcher")
        for i, p in enumerate(self._predictors):
            self._workers.append(_conc.spawn(
                self._worker_loop, args=(p,),
                name=f"serving-worker-{i}"))
        if self.config.warmup == "async":
            _conc.spawn(self._warmup_async, name="serving-warmup")

    def _warmup_async(self):
        try:
            self._warmup()
        finally:
            # an engine closed mid-warmup must stay not-ready: flipping
            # it back would make routers dispatch into EngineClosed
            if not self._closed:
                self.ready = True

    # -- warmup --------------------------------------------------------
    def _warmup(self):
        """Compile (or, with the AOT artifact store armed, deserialize)
        every batch-bucket executable before the first request, so
        first-request latency equals steady state.  Skipped per input
        with dynamic non-batch dims (the bucket set is unbounded there
        unless pad_dynamic_dims bounds it — and then only the batch
        buckets are enumerable anyway)."""
        avals = self._base._meta.get("input_avals") or []
        if len(avals) != len(self.input_names):
            import warnings
            warnings.warn(
                "EngineConfig.warmup: artifact metadata has no usable "
                "input_avals (legacy/storage-reduced artifact?) — "
                "warmup skipped; buckets will compile on first use",
                UserWarning, stacklevel=3)
            return
        shapes = []
        for shape, dt in avals:
            tail = [int(d) if d is not None else -1 for d in shape[1:]]
            if any(d < 0 for d in tail):
                import warnings
                warnings.warn(
                    f"EngineConfig.warmup: input has dynamic non-batch "
                    f"dims {list(shape)}; bucket set is unbounded — "
                    "warmup skipped for this engine", UserWarning,
                    stacklevel=3)
                return
            shapes.append((tail, str(dt)))
        # derive the bucket set from the SAME policy the batcher uses —
        # a second copy of the bucketing rule would silently warm the
        # wrong keys if the rule ever changed
        buckets, rows = [], 1
        while True:
            b = self._policy.batch_bucket(rows)
            buckets.append(b)
            if b >= self.config.max_batch_size:
                break
            rows = b + 1
        errors = []
        for rows in buckets:
            padded = [np.zeros((rows,) + tuple(tail), dt)
                      for tail, dt in shapes]
            try:
                self._run_bucketed(self._base, padded)
            except Exception as e:  # noqa: BLE001 — best-effort, but loud
                errors.append((rows, e))
        if errors:
            import warnings
            warnings.warn(
                f"engine warmup failed for {len(errors)} bucket(s) "
                f"(first: rows={errors[0][0]}: {errors[0][1]!r}); "
                "those buckets will compile on first use",
                RuntimeWarning, stacklevel=3)
        self.warmed_buckets = len(self._cache)
        from ..profiler import metrics as _metrics
        _metrics.gauge(
            f"{self.metrics_prefix}.warmed_buckets",
            "bucket executables pre-populated at engine construction"
        ).set(self.warmed_buckets)

    def memory_breakdown(self) -> Dict[str, int]:
        """The ``/healthz`` memory fields for the batch engine:
        resident parameter bytes plus the process peak census."""
        try:
            pb = _memscope.tree_nbytes(
                getattr(self._base, "_params", None) or {})
        except Exception:   # noqa: BLE001 — health must never raise
            pb = 0
        return {
            "mem_params_bytes": int(pb),
            "mem_peak_step_bytes":
                int(_memscope.peak_bytes()) if _memscope.active else 0,
        }

    # -- client surface ------------------------------------------------
    def submit(self, inputs, deadline_ms: Optional[float] = "default",
               trace_ctx=None) -> Future:
        """Enqueue one request; returns a Future resolving to the list
        of output arrays (np.ndarray, one per model output, sliced to
        this request's rows).  Raises RequestRejected/EngineClosed at
        admission; chaos site ``serve.request`` can fail or delay here.
        ``trace_ctx`` (an rtrace TraceContext, usually built by the
        HTTP layer from the ``traceparent`` header) makes the request's
        admission/queue/execute hops emit request-scoped spans.
        """
        arrays = self._normalize(inputs)
        rows = int(arrays[0].shape[0])
        traced = trace_ctx is not None and _rtrace.active
        t_adm = _tracer.now_ns() if traced else 0
        try:
            from ..utils import chaos as _chaos
            if _chaos.active:
                _chaos.hit("serve.request")
            self._admission.acquire(rows)
        except RequestRejected as e:
            if traced:
                trace_ctx.record("admission", t_adm, outcome=e.reason,
                                 terminated=True)
            raise
        except Exception as e:
            if traced:
                trace_ctx.record("admission", t_adm,
                                 outcome=type(e).__name__,
                                 terminated=True)
            raise
        if traced:
            trace_ctx.record("admission", t_adm, outcome="admitted")
        if deadline_ms == "default":
            deadline_ms = self.config.deadline_ms
        req = _Request(arrays, rows, self._signature(arrays),
                       deadline_from_ms(deadline_ms), ctx=trace_ctx)
        with self._cond:
            if self._closed:
                self._admission.release()
                if traced:
                    trace_ctx.record("queue_wait", req.t_submit_ns,
                                     outcome="closed", terminated=True)
                raise EngineClosed()
            self._pending.append(req)
            self._cond.notify()
        return req.future

    def infer(self, inputs, deadline_ms: Optional[float] = "default",
              timeout: Optional[float] = None,
              trace_ctx=None) -> List[np.ndarray]:
        """Blocking submit; ``timeout`` (seconds) bounds the wait
        independently of the request deadline."""
        fut = self.submit(inputs, deadline_ms=deadline_ms,
                          trace_ctx=trace_ctx)
        try:
            return fut.result(timeout=timeout)
        except (TimeoutError, _FutureTimeout):
            if fut.done():
                # the request finished after all: either the worker beat
                # the wait-timeout by a hair (return its result instead
                # of discarding it) or the error is the request's OWN
                # (shed deadline, model-side timeout) and re-raises here
                return fut.result()
            raise DeadlineExceeded(
                f"no result within {timeout}s (request may still "
                "complete; use submit() for a cancellable future)")

    # -- operations ----------------------------------------------------
    def pause(self):
        """Stop draining the queue (maintenance / deterministic overload
        tests); admission keeps filling up to max_queue, then sheds."""
        with self._cond:
            self._paused = True

    def resume(self):
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def stats(self) -> Dict[str, object]:
        from ..profiler import metrics as _metrics
        snap = _metrics.snapshot()
        return {k: v for k, v in snap.items()
                if k.startswith((self.metrics_prefix + ".",
                                 "inference."))}

    @property
    def occupancy(self) -> int:
        """Requests queued or executing — the live-load signal a fleet
        router's least-loaded dispatch reads from the registry."""
        return self._admission.depth + self._inflight

    def swap_weights(self, params, buffers=None, *,
                     timeout: float = 30.0):
        """Zero-downtime weight hot-swap: replace the pool's shared
        weight set between batches.

        Every ``Predictor.clone()`` in the pool shares ONE weight dict
        through the ``_jit_holder`` contract (identity, not copies), so
        the swap is an in-place update of that dict: quiesce the
        batcher + workers (no batch may sit between its weight read and
        its execute), write the new arrays, resume.  Batches never mix
        weight sets — each executes entirely on the old or entirely on
        the new tree — and queued requests simply wait out the
        (millisecond) quiesce window, so nothing is dropped.
        Executables are untouched: weights are call *arguments*, and
        the tree is validated shape/dtype-exact, so there is no
        recompile and no retrace."""
        base = self._base
        if base._kind != "layer":
            raise RuntimeError(
                "swap_weights needs a layer-kind artifact (program-kind "
                "artifacts carry no serving-side weight set)")
        live = base._materialize_params()
        if live is not base._params:
            raise RuntimeError(
                "swap_weights supports plain-precision artifacts only: "
                "this engine serves a reduced/quantized weight set — "
                "re-quantize offline and roll the artifact instead")
        import jax.numpy as jnp
        new_p = {k: jnp.asarray(getattr(v, "_data", v))
                 for k, v in params.items()}
        _check_swap_tree(live, new_p, "params")
        new_b = None
        if buffers is not None:
            new_b = {k: jnp.asarray(getattr(v, "_data", v))
                     for k, v in buffers.items()}
            _check_swap_tree(base._buffers, new_b, "buffers")
        deadline = time.monotonic() + (timeout or 30.0)
        with self._cond:
            prior_paused = self._paused
            self._paused = True
        try:
            self._quiesce(deadline)
            with base._jit_holder["lock"]:
                live.update(new_p)
                if new_b is not None:
                    base._buffers.update(new_b)
        finally:
            with self._cond:
                self._paused = prior_paused
                self._cond.notify_all()
        from ..profiler import metrics as _metrics
        with self._mlock:
            _metrics.counter(
                f"{self.metrics_prefix}.weight_swaps",
                "zero-downtime weight hot-swaps applied").inc()
        if _flight.active:
            _flight.note("serve", "weights_swap",
                         engine=self.metrics_prefix)

    def _quiesce(self, deadline: float):
        """Wait until no batch is queued or executing (the batcher is
        already paused by the caller).  ``_inflight`` covers a batch
        from before its queue put through the end of its execute, and
        ``_batcher_busy`` covers the assembly window, so predicate
        true == no request is anywhere between weight read and
        result."""
        with self._cond:
            while self._inflight != 0 or self._batcher_busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        "swap_weights could not quiesce the engine: "
                        "in-flight batches did not drain in time")
                self._cond.wait(timeout=remaining)

    def close(self, timeout: Optional[float] = 30.0):
        """Reject new work, drain queued requests, stop the pool."""
        self.ready = False
        self._admission.close()
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._stop = True
            self._paused = False
            self._cond.notify_all()
        self._batcher.join(timeout=timeout)
        for _ in self._workers:
            try:  # a wedged worker must not turn close() into a hang
                self._batch_q.put(None, timeout=timeout)
            except _queue.Full:
                break
        for t in self._workers:
            t.join(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- internals -----------------------------------------------------
    def _normalize(self, inputs) -> List[np.ndarray]:
        if isinstance(inputs, dict):
            missing = [n for n in self.input_names if n not in inputs]
            if missing:
                raise ValueError(f"missing inputs {missing}; model "
                                 f"expects {self.input_names}")
            inputs = [inputs[n] for n in self.input_names]
        inputs = list(inputs) if isinstance(inputs, (list, tuple)) \
            else [inputs]
        if len(inputs) != len(self.input_names):
            raise ValueError(
                f"request has {len(inputs)} inputs but the model takes "
                f"{len(self.input_names)}: {self.input_names}")
        arrays = [np.asarray(a) for a in inputs]
        rows = None
        for n, a in zip(self.input_names, arrays):
            if a.ndim == 0:
                raise ValueError(
                    f"input '{n}' is 0-d; engine inputs are batch-major "
                    "(dim 0 is the sample dim)")
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise ValueError(
                    "inputs disagree on the batch dim: "
                    f"{[tuple(x.shape) for x in arrays]}")
        if rows == 0:
            raise ValueError("empty request (0 rows)")
        return arrays

    def _signature(self, arrays) -> tuple:
        """Requests coalesce only when their padded non-batch dims and
        dtypes match — the concatenated batch must be rectangular."""
        sig = []
        for i, a in enumerate(arrays):
            tail = self._policy.bucket_shape(i, a.shape, 0)[1:]
            sig.append((tail, str(a.dtype)))
        return tuple(sig)

    @staticmethod
    def _complete(fut: Future, result=None, exc=None) -> bool:
        """Resolve a request future, tolerating client-side cancel():
        a cancelled future must never blow up the batcher/worker
        pipeline.  Returns False when the client cancelled first."""
        if not fut.set_running_or_notify_cancel():
            return False
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
        return True

    def _shed(self, req: _Request):
        with self._mlock:                # batcher AND workers shed
            self._admission.shed_deadline()
        if req.ctx is not None and _rtrace.active:
            req.ctx.record("queue_wait", req.t_submit_ns,
                           outcome="shed_deadline", terminated=True)
        self._complete(req.future, exc=DeadlineExceeded(
            "request deadline expired while queued (engine overloaded "
            "relative to the deadline)"))

    def _batcher_loop(self):
        timeout_s = self.config.batch_timeout_ms / 1e3
        while True:
            with self._cond:
                # no timeout needed: submit/resume/close all notify, so
                # an idle engine parks instead of polling at 10 Hz
                while (not self._pending or self._paused) \
                        and not self._stop:
                    self._cond.wait()
                if not self._pending and self._stop:
                    break
                if self._paused and not self._stop:
                    continue
                first = self._pending.popleft()
                self._batcher_busy = True
            self._admission.release()
            if first.expired():
                self._shed(first)
                self._batcher_idle()
                continue
            batch = [first]
            rows = first.rows
            if timeout_s <= 0:
                # batch-less mode (documented solo-exact numerics for
                # single-row requests): never coalesce, dispatch as-is
                self._dispatch_batch(batch)
                continue
            t_close = time.monotonic() + timeout_s
            while rows < self.config.max_batch_size:
                with self._cond:
                    took = []
                    for r in list(self._pending):
                        if r.sig == first.sig and \
                                rows + r.rows <= self.config.max_batch_size:
                            self._pending.remove(r)
                            took.append(r)
                            rows += r.rows
                    # a compatible request that no longer FITS means the
                    # batch is capacity-done: ship it now, don't idle out
                    # the timeout window
                    fit_limited = any(r.sig == first.sig
                                      for r in self._pending)
                for r in took:
                    self._admission.release()
                    if r.expired():
                        self._shed(r)
                        rows -= r.rows
                    else:
                        batch.append(r)
                if rows >= self.config.max_batch_size or self._stop \
                        or fit_limited:
                    break
                remaining = t_close - time.monotonic()
                if remaining <= 0:
                    break
                with self._cond:
                    # wait even when incompatible requests sit queued —
                    # they belong to the NEXT batch; new arrivals notify
                    # and re-trigger the scan (worst case one timeout
                    # window of extra latency, never a busy spin)
                    self._cond.wait(timeout=remaining)
            self._dispatch_batch(batch)

    def _dispatch_batch(self, batch):
        """Hand one assembled batch to the worker pool.  ``_inflight``
        is incremented BEFORE the queue put, so the quiesce predicate
        (``_quiesce``) can never observe an empty queue while a batch
        is between the batcher and a worker."""
        with self._cond:
            self._inflight += 1
        self._batch_q.put(batch)
        self._batcher_idle()

    def _batcher_idle(self):
        with self._cond:
            self._batcher_busy = False
            self._cond.notify_all()

    def _worker_loop(self, predictor):
        while True:
            batch = self._batch_q.get()
            if batch is None:
                break
            try:
                self._execute_batch(predictor, batch)
            except BaseException as e:  # noqa: BLE001 - fan the error out
                for r in batch:
                    if not r.future.done():
                        try:
                            r.future.set_exception(e)
                            with self._mlock:
                                self._m_failed.inc()
                        except Exception:  # cancelled concurrently
                            pass
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _execute_batch(self, predictor, batch: List[_Request]):
        now = time.monotonic()
        live = []
        for r in batch:
            if r.future.cancelled():
                continue                 # client gave up; don't compute
            if r.expired(now):
                self._shed(r)
            else:
                live.append(r)
        if not live:
            return
        rows = sum(r.rows for r in live)
        bucket = self._policy.batch_bucket(rows)
        padded = []
        for i in range(len(self.input_names)):
            # one zero-filled bucket allocation per input; each request
            # writes its rows (and, under pad_dynamic_dims, its tail
            # sub-extent) straight into place — no per-request pad
            # copies, no concatenate
            tail = live[0].sig[i][0]
            buf = np.zeros((bucket,) + tail, live[0].arrays[i].dtype)
            off = 0
            for r in live:
                a = r.arrays[i]
                buf[(slice(off, off + r.rows),)
                    + tuple(slice(0, s) for s in a.shape[1:])] = a
                off += r.rows
            padded.append(buf)
        with self._mlock:
            for r in live:
                self._m_qwait.observe((now - r.t_submit) * 1e3)
            self._m_occupancy.observe(rows)
            self._m_fill.observe(rows / bucket)
            self._m_pad_waste.observe((bucket - rows) / bucket)
            self._m_batches.inc()

        traced = [r for r in live if r.ctx is not None] \
            if _rtrace.active else []
        t0 = _tracer.now_ns() if traced else 0
        for r in traced:
            r.ctx.record("queue_wait", r.t_submit_ns, t0)
        outs = self._run_bucketed(predictor, padded)
        outs = [np.asarray(o) for o in outs]
        if traced:
            # fan-in causality: ONE span for the fused batch, each
            # member's own 'execute' span pointing back at it
            t1 = _tracer.now_ns()
            bspan = _rtrace.batch_span(
                "batch::execute", t0, t1, [r.ctx for r in traced],
                rows=rows, bucket=bucket)
            for r in traced:
                r.ctx.record("execute", t0, t1, batch_span=bspan,
                             rows=r.rows)
        off = 0
        done_t = time.monotonic()
        for r in live:
            # copy strict sub-slices: a client holding its rows must not
            # pin the whole bucket-sized output array (padding included)
            result = [o if o.ndim == 0 or (off == 0 and
                                           r.rows == o.shape[0])
                      else o[off:off + r.rows].copy()
                      for o in outs]
            off += r.rows
            if self._complete(r.future, result=result):
                with self._mlock:
                    self._m_done.inc()
                    self._m_latency.observe((done_t - r.t_submit) * 1e3)

    def _run_bucketed(self, predictor, padded: List[np.ndarray]):
        import jax
        import jax.numpy as jnp
        arrays = [jnp.asarray(a) for a in padded]
        key = (tuple((a.shape, str(a.dtype)) for a in arrays),
               self._precision)
        leading = [predictor._materialize_params(),
                   predictor._buffers] if predictor._kind == "layer" \
            else []

        def compile_fn():
            jit_fn = predictor._compiled_call()
            try:
                avals = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                         for a in arrays]
                lead_avals = [jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), t)
                    for t in leading]
                # AOT artifact store: an engine relaunch loads the
                # persisted executable instead of re-compiling the
                # bucket (utils/artifact_store.py; armed with
                # FLAGS_compile_cache_dir)
                from ..utils.artifact_store import aot_compile
                return aot_compile(
                    jit_fn.lower(*lead_avals, *avals),
                    label=f"{self.config.name}.bucket")
            except Exception:
                # AOT lowering unsupported for this export: fall back to
                # the shared jit wrapper (its shape-keyed cache makes the
                # first call the compile, still once per bucket key)
                return jit_fn
        exe = self._cache.get_or_compile(key, compile_fn)
        out = exe(*leading, *arrays)
        return predictor._finalize_outputs(out)


def _check_swap_tree(live: Dict[str, object], new: Dict[str, object],
                     what: str):
    """A hot-swap may never half-apply: the incoming tree must match
    the live one key-for-key in shape and dtype.  Weights are
    executable *arguments* — a mismatched swap would mean silent
    retraces (new compiles mid-traffic) or shape errors inside a live
    batch, so it is rejected wholesale before anything is touched."""
    missing = sorted(set(live) - set(new))
    extra = sorted(set(new) - set(live))
    if missing or extra:
        raise ValueError(
            f"swap_weights {what} tree mismatch: missing {missing[:3]}"
            f"{'...' if len(missing) > 3 else ''}, unexpected "
            f"{extra[:3]}{'...' if len(extra) > 3 else ''} — swap "
            "trees must match the served model exactly")
    for k, v in new.items():
        cur = live[k]
        if tuple(v.shape) != tuple(cur.shape) or \
                str(v.dtype) != str(cur.dtype):
            raise ValueError(
                f"swap_weights {what}[{k!r}]: incoming "
                f"{tuple(v.shape)}/{v.dtype} vs served "
                f"{tuple(cur.shape)}/{cur.dtype} — a shape/dtype "
                "change is a new artifact, not a hot-swap")


# ---------------------------------------------------------------------------
# continuous (in-flight) batching for autoregressive generation
# ---------------------------------------------------------------------------

class GenerationEngineConfig:
    """Knobs for :class:`GenerationEngine`.

    max_slots            rows of the running decode batch (the slot
                         count); every compiled step has exactly this
                         batch shape, so empty slots cost compute but
                         never a recompile
    max_length           KV-cache capacity per slot (prompt + generated
                         tokens); defaults to the model's max_seq_len
    max_new_tokens       per-request default generation budget
    max_queue            admission bound on waiting requests (default:
                         FLAGS_serving_queue_depth)
    max_tokens_in_flight token-budget admission bound: the sum of every
                         admitted request's (prompt_len +
                         max_new_tokens) reservation; default
                         max_slots * max_length (i.e. "what the cache
                         can physically hold")
    deadline_ms          default per-request deadline (sheds while
                         queued, like the batch engine); None = none
    aging_s              priority-aging interval for the queue: a
                         waiting request's effective priority improves
                         one class per ``aging_s`` seconds, so batch
                         traffic is delayed under interactive bursts
                         but can never starve (0 disables aging —
                         strict priority order)
    tenant_quotas        per-tenant token-bucket table
                         ``{tenant: {"rate": tokens/s, "burst": max}}``
                         (``"*"`` = default for unlisted tenants);
                         exhaustion sheds typed ``tenant_quota``.
                         Hot-reloadable at runtime via
                         ``engine.set_quotas`` / admission.QuotaWatcher
    prompt_bucket_min    smallest prompt-length bucket (prefill
                         executables are one-per-bucket)
    warmup               pre-populate the decode executable and every
                         prompt-bucket prefill executable at
                         construction (from the AOT artifact store when
                         FLAGS_compile_cache_dir is armed), so
                         time-to-first-token equals steady state from
                         request one; warmed count lands in /healthz.
                         ``"async"`` warms on a background thread and
                         holds ``ready=False`` until done (routers
                         treat not-ready as undispatchable)
    name                 metrics prefix (default "serving" — gives the
                         ``serving.prefill`` / ``serving.decode`` /
                         ``serving.compile`` names the gates assert on)

    Paged-KV knobs (read by :class:`PagedGenerationEngine` only; the
    contiguous engine ignores them):

    block_size           KV block width in tokens; must divide
                         max_length (bit-parity vs contiguous needs the
                         gathered view capacity == contiguous capacity)
    num_blocks           the arena's block-pool size — THE serving HBM
                         budget.  Default max_slots * (max_length /
                         block_size), i.e. the contiguous engine's
                         worst-case footprint; provision it for the
                         expected live tokens instead and the same HBM
                         carries a multiple of the streams
    kv_cache_dtype       'float32' | 'int8' block storage; default
                         reads FLAGS_kv_cache_dtype at construction
    prefix_cache_blocks  content-addressed prefix-cache capacity in
                         blocks (0 disables); default reads
                         FLAGS_prefix_cache_blocks
    speculative_k        draft tokens per decode step from the n-gram
                         prompt-lookup drafter (0 disables); default
                         reads FLAGS_speculative_k
    spec_ngram           trailing n-gram width the drafter matches
    """

    def __init__(self, max_slots: int = 4,
                 max_length: Optional[int] = None,
                 max_new_tokens: int = 64,
                 max_queue: Optional[int] = None,
                 max_tokens_in_flight: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 aging_s: float = 2.0,
                 tenant_quotas: Optional[Dict[str, dict]] = None,
                 prompt_bucket_min: int = 8,
                 warmup: bool = False,
                 name: str = "serving",
                 block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 kv_cache_dtype: Optional[str] = None,
                 prefix_cache_blocks: Optional[int] = None,
                 speculative_k: Optional[int] = None,
                 spec_ngram: int = 2):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = int(max_slots)
        self.max_length = max_length
        self.max_new_tokens = int(max_new_tokens)
        from ..utils import flags as _flags
        if max_queue is None:
            max_queue = int(_flags.get_flag("FLAGS_serving_queue_depth"))
        self.max_queue = int(max_queue)
        self.max_tokens_in_flight = max_tokens_in_flight
        self.deadline_ms = deadline_ms
        self.aging_s = float(aging_s)
        self.tenant_quotas = tenant_quotas
        self.prompt_bucket_min = int(prompt_bucket_min)
        self.warmup = warmup if warmup == "async" else bool(warmup)
        self.name = str(name)
        self.block_size = int(block_size)
        self.num_blocks = num_blocks
        if kv_cache_dtype is None:
            kv_cache_dtype = str(_flags.get_flag("FLAGS_kv_cache_dtype"))
        self.kv_cache_dtype = kv_cache_dtype
        if prefix_cache_blocks is None:
            prefix_cache_blocks = int(
                _flags.get_flag("FLAGS_prefix_cache_blocks"))
        self.prefix_cache_blocks = int(prefix_cache_blocks)
        if speculative_k is None:
            speculative_k = int(_flags.get_flag("FLAGS_speculative_k"))
        self.speculative_k = int(speculative_k)
        self.spec_ngram = int(spec_ngram)


class _GenRequest:
    __slots__ = ("prompt", "max_new", "temperature", "top_k", "top_p",
                 "seed", "eos", "deadline", "budget", "future", "queue",
                 "tokens", "t_submit", "t_first", "t_last", "cancelled",
                 "blocks", "cached_len", "ctx", "t_submit_ns",
                 "finish_reason", "tenant", "priority", "parked")

    def __init__(self, prompt, max_new, temperature, top_k, top_p,
                 seed, eos, deadline, budget, ctx=None, tenant=None,
                 priority=1):
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed
        self.eos = eos
        self.deadline = deadline
        self.budget = budget
        self.future: Future = Future()
        self.queue: "_queue.Queue" = _queue.Queue()
        self.tokens: List[int] = []
        self.t_submit = time.monotonic()
        self.t_first = None
        self.t_last = None
        self.cancelled = False
        self.blocks: List[int] = []    # paged mode: held KV block ids
        self.cached_len = 0            # paged mode: prefix-cache cover
        # request-trace context (profiler/rtrace.py) carried across the
        # submit -> scheduler -> stream thread hops; None when untraced
        self.ctx = ctx
        self.t_submit_ns = _tracer.now_ns() if ctx is not None else 0
        self.finish_reason: Optional[str] = None
        self.tenant: Optional[str] = tenant
        self.priority = int(priority)   # rank into admission.PRIORITIES
        # preemption park state: {"host": payload, "nblocks": n,
        # "pos": absolute position, "last": last sampled token} while
        # the request sits swapped out in host memory; None otherwise
        self.parked: Optional[dict] = None

    @property
    def request_id(self) -> Optional[str]:
        return self.ctx.request_id if self.ctx is not None else None

    @property
    def priority_name(self) -> str:
        return PRIORITIES[self.priority]

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) > self.deadline


class GenerationStream:
    """Handle for one in-flight generation request.

    Iterate it to consume tokens as the engine emits them (ends when
    the request finishes; raises the request's error if it failed), or
    call :meth:`result` to block for the full generated sequence.
    ``cancel()`` asks the scheduler to retire the request at the next
    token boundary — the future then resolves to the partial tokens.
    """

    def __init__(self, req: _GenRequest):
        self._req = req

    def __iter__(self):
        while True:
            item = self._req.queue.get()
            if item is None:
                return
            if isinstance(item, BaseException):
                raise item
            yield item

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Generated token ids (prompt excluded; eos, when hit,
        included as the last element)."""
        try:
            return self._req.future.result(timeout=timeout)
        except (TimeoutError, _FutureTimeout):
            if self._req.future.done():
                return self._req.future.result()
            raise DeadlineExceeded(
                f"no result within {timeout}s (generation may still "
                "be running; iterate the stream for partial tokens)")

    def cancel(self):
        self._req.cancelled = True

    @property
    def done(self) -> bool:
        return self._req.future.done()


class GenerationEngine:
    """Continuous (in-flight) batching over an autoregressive model.

    The PR 4 :class:`InferenceEngine` coalesces independent one-shot
    requests; LLM chat traffic is iterative — each request is a decode
    LOOP whose length nobody knows up front.  Batching whole requests
    would make every short request wait for the longest batchmate.
    This engine batches at **token boundaries** instead (Orca-style):

    - a fixed bank of ``max_slots`` decode slots runs one fused
      fixed-shape decode step per token for every occupied slot;
    - queued requests are admitted into free slots BETWEEN decode
      steps: their prompts are prefilled (grouped per prompt-length
      bucket) directly into the shared fixed-capacity KV-cache without
      touching running neighbours (``update_mask`` merge);
    - finished rows (eos / token budget / cache full) retire
      immediately and their slot is re-admitted next boundary — the
      batch never drains to refill;
    - tokens stream out per request as they are sampled
      (:class:`GenerationStream`; the HTTP layer exposes SSE).

    Because rows never interact (see ``generation/sampling.py``) and
    every step runs at the same ``(max_slots, ...)`` shapes as a
    solo :meth:`GenerationSession.generate` call over the same session,
    each streamed sequence is **bit-identical** to the sequential
    ``generate()`` reference — chaos soak in ``tools/decode_gate.py``
    pins exactly that.

    Admission extends PR 4's queue-depth bound with a **token budget**
    (``max_tokens_in_flight``): requests reserve prompt + max_new
    tokens at submit and return them at retirement, so overload sheds
    in the unit the hardware is actually provisioned in.

    Metrics (PR 1 registry, ``<name>.`` prefix): ``prefill``/``decode``
    step histograms, ``ttft_ms``, ``inter_token_ms``,
    ``decode.occupancy``, ``tokens_out``, ``compile`` + the admission
    SLO counters.
    """

    def __init__(self, model, config: Optional[GenerationEngineConfig]
                 = None):
        self.config = config or GenerationEngineConfig()
        cfg = self.config
        self.model = model
        max_len = int(cfg.max_length or model.cfg.max_seq_len)
        self.session = self._make_session(model, cfg, max_len)
        self.max_length = self.session.max_length
        S = self.slots = self.session.batch_capacity
        self.metrics_prefix = cfg.name
        self._admission = self._make_admission(cfg)

        from ..profiler import metrics as _metrics
        p = cfg.name
        self._m_ttft = _metrics.histogram(
            f"{p}.ttft_ms", "time to first token (submit -> first "
            "sampled token)")
        self._m_itl = _metrics.histogram(
            f"{p}.inter_token_ms", "gap between consecutive streamed "
            "tokens of one request")
        self._m_occ = _metrics.histogram(
            f"{p}.decode.occupancy", "occupied slots per decode step")
        self._m_done = _metrics.counter(
            f"{p}.request.completed", "requests answered successfully")
        self._m_failed = _metrics.counter(
            f"{p}.request.failed", "requests completed exceptionally")
        self._m_cancelled = _metrics.counter(
            f"{p}.request.cancelled", "requests retired by client "
            "cancel (future resolves to the partial tokens; not "
            "counted as completed — SLO dashboards must not mistake "
            "disconnects for answers)")
        _metrics.gauge(f"{p}.slots", "decode slots").set(S)

        # warmup BEFORE the slot bank exists: the warmup cache is a
        # local that frees on return, so peak device memory stays at
        # one KV cache either way (async warmup trades that guarantee
        # for immediate liveness + an honest ready=False window)
        self.ready = False
        self.warmed_buckets = 0
        if cfg.warmup and cfg.warmup != "async":
            self._warmup()
            self.ready = True
        elif not cfg.warmup:
            self.ready = True

        # slot bank (host-side control state; caches live on device)
        self._init_slot_state()

        self._pending: deque = deque()
        # requests preempted out of their decode slots to host memory
        # (paged engine only; the base engine never parks anything)
        self._parked: List[_GenRequest] = []
        # KV chains shipped in from a prefill replica, queued for the
        # scheduler thread to swap into the arenas at a token boundary
        # (paged engine only — the arenas are loop-thread state, so an
        # HTTP thread may never write them directly)
        self._imports: List[dict] = []
        self._aging_s = float(cfg.aging_s)
        self._cond = _conc.Condition(name=f"{cfg.name}"
                                     ".genengine.cond")
        self._mlock = _conc.Lock(name=f"{cfg.name}.genengine.metrics")
        self._stop = False
        self._paused = False
        self._closed = False
        # pending weight swap, applied by the scheduler BETWEEN token
        # boundaries: (params, buffers, done_event, error_holder)
        self._swap = None
        self._scheduler = _conc.spawn(
            self._loop, name="generation-scheduler")
        if cfg.warmup == "async":
            _conc.spawn(self._warmup_async, name="generation-warmup")

    def _warmup_async(self):
        try:
            self._warmup()
        finally:
            # an engine closed mid-warmup must stay not-ready: flipping
            # it back would make routers dispatch into EngineClosed
            if not self._closed:
                self.ready = True

    # -- construction hooks (PagedGenerationEngine overrides these) ----
    def _make_session(self, model, cfg: GenerationEngineConfig,
                      max_len: int):
        from ..generation import GenerationSession
        return GenerationSession(
            model, batch_capacity=cfg.max_slots, max_length=max_len,
            prompt_bucket_min=cfg.prompt_bucket_min, name=cfg.name)

    def _make_admission(self, cfg: GenerationEngineConfig
                        ) -> AdmissionController:
        budget = cfg.max_tokens_in_flight
        if budget is None:
            budget = self.slots * self.max_length
        return AdmissionController(
            cfg.max_queue, max_rows=None, name=cfg.name,
            max_tokens=int(budget),
            quotas=self._make_quotas(cfg))

    @staticmethod
    def _make_quotas(cfg: GenerationEngineConfig):
        if not cfg.tenant_quotas:
            return None
        return TenantQuotaTable(cfg.tenant_quotas)

    def set_quotas(self, quotas) -> int:
        """Hot-swap the per-tenant quota table (dict of tenant ->
        ``{"rate": tokens/s, "burst": tokens}``, a built
        :class:`TenantQuotaTable`, or None to drop quota enforcement).
        Validated before publication; in-flight bucket levels carry
        over clamped to the new burst.  Returns the table generation.
        This is the :class:`QuotaWatcher` apply hook — throttle a
        tenant without a restart."""
        return self._admission.set_quotas(quotas)

    def _init_slot_arrays(self):
        S = self.slots
        self._slot_req: List[Optional[_GenRequest]] = [None] * S
        self._positions = np.zeros((S,), np.int32)
        self._last_tok = np.zeros((S,), np.int32)
        self._keys = np.zeros((S, 2), np.uint32)
        self._temps = np.zeros((S,), np.float32)
        self._tks = np.zeros((S,), np.int32)
        self._tps = np.ones((S,), np.float32)

    def _init_slot_state(self):
        self._caches = self.session.init_caches()
        self._init_slot_arrays()
        if _memscope.active:
            self._note_memory_tags()

    # -- memory accounting --------------------------------------------
    def _kv_arena_bytes(self) -> int:
        """Device bytes held by the KV store (the contiguous engine's
        per-slot cache bank; the paged engine overrides with the
        block-pool arena)."""
        return _memscope.tree_nbytes(getattr(self, "_caches", None))

    def _params_bytes(self) -> int:
        try:
            return _memscope.tree_nbytes(self.model.functional_state())
        except Exception:       # noqa: BLE001 — accounting never throws
            return 0

    def _note_memory_tags(self):
        """Attribute this engine's exactly-known footprints to the
        memscope tags (callers gate on the predicate)."""
        _memscope.set_tag_bytes("params", self._params_bytes())
        _memscope.set_tag_bytes("kv_arena", self._kv_arena_bytes())

    def memory_breakdown(self) -> Dict[str, int]:
        """The ``/healthz`` memory fields: where this engine's HBM
        goes, next to the ``kv_blocks_*`` capacity signals.  The peak
        field samples the census only when accounting is armed, so
        unflagged health probes stay attribute-math cheap."""
        return {
            "mem_params_bytes": self._params_bytes(),
            "mem_kv_arena_bytes": self._kv_arena_bytes(),
            "mem_prefix_cache_bytes": 0,
            "mem_peak_step_bytes":
                _memscope.peak_bytes() if _memscope.active else 0,
        }

    def _token_reservation(self, prompt, max_new: int) -> int:
        """Tokens to reserve against the admission budget at submit —
        the contiguous engine's worst case (prompt + max_new; a slot
        physically holds that many cache rows whether used or not).
        The paged engine returns 0: its admission signal is live
        block-pool occupancy, not a worst-case reservation."""
        return int(prompt.size) + int(max_new)

    def _warmup(self):
        """One masked-out prefill per prompt bucket plus one decode
        step over throwaway caches: populates the session's executable
        cache (through the AOT artifact store when armed) without
        touching any real slot state.  All-False update masks keep the
        warmup mathematically inert; ``live_rows=0`` keeps it out of
        the token metrics."""
        from .bucketing import seq_buckets
        S = self.slots
        keys = np.zeros((S, 2), np.uint32)
        temps = np.zeros((S,), np.float32)
        tks = np.zeros((S,), np.int32)
        tps = np.ones((S,), np.float32)
        errors = []
        caches = self.session.init_caches()
        for pb in seq_buckets(self.max_length,
                              self.config.prompt_bucket_min):
            try:
                _tok, caches = self.session.prefill(
                    caches, np.zeros((S, pb), np.int32),
                    np.ones((S,), np.int32), np.zeros((S,), bool),
                    keys, temps, tks, tps)
            except Exception as e:  # noqa: BLE001 — best-effort, but loud
                errors.append((f"prefill:{pb}", e))
        try:
            self.session.decode(
                caches, np.zeros((S,), np.int32),
                np.zeros((S,), np.int32), keys, temps, tks, tps,
                live_rows=0)
        except Exception as e:      # noqa: BLE001
            errors.append(("decode", e))
        self._finish_warmup(errors)

    def _finish_warmup(self, errors):
        """Shared warmup tail (both engine flavors): loud best-effort
        failure report + the ``decode_warmed_buckets`` gauge."""
        if errors:
            import warnings
            warnings.warn(
                f"{type(self).__name__} warmup failed for "
                f"{len(errors)} step(s) (first: {errors[0][0]}: "
                f"{errors[0][1]!r}); those buckets will compile on "
                "first use", RuntimeWarning, stacklevel=4)
        self.warmed_buckets = len(self.session._cache)
        from ..profiler import metrics as _metrics
        # decode_-prefixed: a dual-engine server with both configs at
        # the default name='serving' must not have the batch engine's
        # warmed_buckets gauge overwritten (mirrors the /healthz key)
        _metrics.gauge(
            f"{self.metrics_prefix}.decode_warmed_buckets",
            "prefill/decode executables pre-populated at engine "
            "construction").set(self.warmed_buckets)

    # -- client surface ------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               do_sample: bool = False, temperature: float = 1.0,
               top_k: int = 0, top_p: float = 1.0, seed: int = 0,
               eos_token_id: Optional[int] = None,
               deadline_ms: Optional[float] = "default",
               trace_ctx=None, tenant: Optional[str] = None,
               priority: Optional[str] = None) -> GenerationStream:
        """Enqueue one prompt; returns a :class:`GenerationStream`.
        Raises :class:`RequestRejected` at admission (``queue_full`` /
        ``token_budget`` / ``too_large`` / ``tenant_quota`` /
        ``closed``); the ``serve.request`` chaos site can fail or
        delay here.  ``tenant`` charges the request against that
        tenant's token bucket (when quotas are configured) and labels
        its per-tenant metrics; ``priority`` is one of
        ``admission.PRIORITIES`` ("interactive" < "standard" <
        "batch") and orders dequeue — lower classes only run when no
        higher class is waiting, subject to bounded aging
        (``aging_s``).  ``trace_ctx`` (an rtrace TraceContext, usually
        built by the HTTP layer from ``traceparent``/``X-Request-Id``)
        makes every hop of this request — admission verdict, queue
        wait, prefill, each decode boundary — emit request-scoped
        spans."""
        prompt = np.asarray(getattr(prompt, "_data", prompt))
        prompt = prompt.reshape(-1).astype(np.int32)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        max_new = int(max_new_tokens if max_new_tokens is not None
                      else self.config.max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        rank = priority_rank(priority)   # ValueError on unknown class
        traced = trace_ctx is not None and _rtrace.active
        t_adm = _tracer.now_ns() if traced else 0
        budget = self._token_reservation(prompt, max_new)
        # the quota charge is the request's true worst-case token cost
        # regardless of engine flavor (the paged engine's admission
        # budget is 0 — occupancy-driven — but a tenant's bucket must
        # still drain by what the request can consume)
        quota_cost = int(prompt.size) + max_new
        try:
            if prompt.size >= self.max_length:
                # route through the controller so the per-reason counter
                # and its lock discipline apply (the gates assert exact
                # counts)
                self._admission._reject(
                    "too_large",
                    f"prompt of {prompt.size} tokens leaves no room in "
                    f"the {self.max_length}-slot KV-cache")
            from ..utils import chaos as _chaos
            if _chaos.active:
                _chaos.hit("serve.request")
            self._admission.acquire(
                tokens=budget, tenant=tenant,
                priority=PRIORITIES[rank], quota_tokens=quota_cost)
        except RequestRejected as e:
            if traced:
                # a rejected request still leaves a terminated span
                # carrying the verdict — post-mortems start from WHY
                trace_ctx.record("admission", t_adm, outcome=e.reason,
                                 terminated=True)
            raise
        except Exception as e:
            if traced:
                trace_ctx.record("admission", t_adm,
                                 outcome=type(e).__name__,
                                 terminated=True)
            raise
        if traced:
            trace_ctx.record("admission", t_adm, outcome="admitted")
        if deadline_ms == "default":
            deadline_ms = self.config.deadline_ms
        req = _GenRequest(
            prompt, max_new,
            float(temperature) if do_sample else 0.0, int(top_k),
            float(top_p), int(seed), eos_token_id,
            deadline_from_ms(deadline_ms), budget, ctx=trace_ctx,
            tenant=tenant, priority=rank)
        with self._cond:
            if self._closed:
                self._admission.release()
                self._admission.release_tokens(budget)
                if traced:
                    trace_ctx.record("queue_wait", req.t_submit_ns,
                                     outcome="closed", terminated=True)
                raise EngineClosed()
            self._pending.append(req)
            self._cond.notify()
        return GenerationStream(req)

    def generate(self, prompt, timeout: Optional[float] = None,
                 **kw) -> np.ndarray:
        """Blocking submit: the full generated sequence."""
        return self.submit(prompt, **kw).result(timeout=timeout)

    # -- operations ----------------------------------------------------
    def pause(self):
        """Stop admitting queued requests into slots (running slots
        keep decoding); admission keeps filling up to the bounds, then
        sheds — the deterministic-overload test hook."""
        with self._cond:
            self._paused = True

    def resume(self):
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def stats(self) -> Dict[str, object]:
        from ..profiler import metrics as _metrics
        snap = _metrics.snapshot()
        return {k: v for k, v in snap.items()
                if k.startswith(self.metrics_prefix + ".")}

    @property
    def occupancy(self) -> int:
        """Occupied decode slots — the live-load signal a fleet
        router's least-loaded dispatch reads from the registry."""
        return sum(1 for r in self._slot_req if r is not None)

    @property
    def parked(self) -> int:
        """Requests currently preempted to host memory (paged engine
        only; always 0 on the contiguous engine)."""
        return len(self._parked)

    def swap_weights(self, params, buffers=None, *,
                     timeout: float = 60.0):
        """Zero-downtime weight hot-swap: replace the model's weight
        set BETWEEN engine steps.

        The scheduler applies the swap at the next token boundary (or
        immediately when idle): running decodes finish their current
        fused step on the old weights, every subsequent prefill/decode
        reads the new ones — no stream drops, no slot resets, and no
        recompile (the session's executables take weights as
        *arguments*; the tree is validated shape/dtype-exact first).
        Blocks until the scheduler has applied the swap; raises the
        application error if it failed (the old weights stay live)."""
        import jax.numpy as jnp
        cur_p, cur_b = self.model.functional_state()
        new_p = {k: jnp.asarray(getattr(v, "_data", v))
                 for k, v in params.items()}
        _check_swap_tree(cur_p, new_p, "params")
        new_b = None
        if buffers is not None:
            new_b = {k: jnp.asarray(getattr(v, "_data", v))
                     for k, v in buffers.items()}
            _check_swap_tree(cur_b, new_b, "buffers")
        done = threading.Event()
        holder: Dict[str, BaseException] = {}
        with self._cond:
            if self._closed:
                raise EngineClosed()
            if self._swap is not None:
                raise RuntimeError(
                    "another weight swap is already pending")
            self._swap = (new_p, new_b, done, holder)
            self._cond.notify_all()
        if not done.wait(timeout):
            with self._cond:
                if self._swap is not None and self._swap[2] is done:
                    # withdrawn before the scheduler claimed it: the
                    # swap will never apply, the timeout is honest
                    self._swap = None
                    raise TimeoutError(
                        f"weight swap not applied within {timeout}s "
                        "(scheduler wedged mid-step?)")
            # the scheduler popped the swap while we timed out — it is
            # applying RIGHT NOW; raising here would leave the caller
            # believing the old weights are live while the served set
            # flips under it.  Wait the application out.
            if not done.wait(timeout):
                raise TimeoutError(
                    f"weight swap claimed by the scheduler but not "
                    f"applied within another {timeout}s (model rebind "
                    "wedged?)")
        err = holder.get("error")
        if err is not None:
            raise err
        from ..profiler import metrics as _metrics
        with self._mlock:
            _metrics.counter(
                f"{self.metrics_prefix}.weight_swaps",
                "zero-downtime weight hot-swaps applied").inc()
        if _flight.active:
            _flight.note("serve", "weights_swap",
                         engine=self.metrics_prefix)

    def _apply_swap(self):
        """Scheduler-side swap application — called only between
        boundaries, on the scheduler thread, so no executable is
        mid-step while the model's arrays are rebound."""
        with self._cond:
            swap, self._swap = self._swap, None
        if swap is None:
            return
        new_p, new_b, done, holder = swap
        try:
            self.model.load_functional_state(new_p, new_b)
        except BaseException as e:     # noqa: BLE001 — surfaced to caller
            holder["error"] = e
        finally:
            done.set()

    def _drain_swap(self, exc: BaseException):
        """Resolve a swap the scheduler will never apply (engine
        closing) so the caller's wait can't hang."""
        with self._cond:
            swap, self._swap = self._swap, None
        if swap is not None:
            swap[3]["error"] = exc
            swap[2].set()

    def close(self, timeout: Optional[float] = 60.0):
        """Reject new work, let queued + running requests finish, stop
        the scheduler."""
        self.ready = False
        self._admission.close()
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._stop = True
            self._paused = False
            self._cond.notify_all()
        self._scheduler.join(timeout=timeout)
        self._drain_swap(EngineClosed())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- scheduler -----------------------------------------------------
    def _occupied(self) -> List[int]:
        return [i for i, r in enumerate(self._slot_req) if r is not None]

    def _loop(self):
        while True:
            with self._cond:
                while self._swap is None and not self._imports and \
                        ((not self._stop and not self._pending
                          and not self._parked
                          and not self._occupied()) or
                         (self._paused and not self._occupied()
                          and not self._stop)):
                    self._cond.wait()
                if self._stop and not self._pending \
                        and not self._parked and not self._occupied():
                    break
            if self._swap is not None:
                # between boundaries by construction: the previous
                # fused step has returned, the next hasn't dispatched
                self._apply_swap()
                continue
            try:
                self._admit()
                occ = self._occupied()
                if not occ:
                    continue
                self._decode_round(occ)
            except BaseException as e:  # noqa: BLE001 — fail everything in flight
                self._fail_all(e)

    def _trace_boundary(self, name: str, t0: int, t1: int,
                        slots: List[int], **fields):
        """Request-trace accounting for one fused engine step: ONE
        ``batch::<name>`` span linked to every traced member's root
        (fan-in causality) plus a per-member ``<name>`` child span
        pointing back at it.  Call sites gate on ``_rtrace.active``."""
        ctxs, reqs = [], []
        for s in slots:
            r = self._slot_req[s]
            if r is not None and r.ctx is not None:
                ctxs.append(r.ctx)
                reqs.append((s, r))
        if not ctxs:
            return
        bspan = _rtrace.batch_span(f"batch::{name}", t0, t1, ctxs,
                                   **fields)
        for s, r in reqs:
            r.ctx.record(name, t0, t1, batch_span=bspan, slot=s,
                         position=int(self._positions[s]))

    def _decode_round(self, occ: List[int]):
        """One token boundary: a fused decode step for every occupied
        slot (the paged engine overrides this with block-table decode
        and, when armed, speculative verify)."""
        t0 = _tracer.now_ns() if _rtrace.active else 0
        tok, self._caches = self.session.decode(
            self._caches, self._last_tok, self._positions,
            self._keys, self._temps, self._tks, self._tps,
            live_rows=len(occ))
        if t0:
            self._trace_boundary("decode", t0, _tracer.now_ns(), occ,
                                 occupancy=len(occ))
        with self._mlock:
            self._m_occ.observe(len(occ))
        self._positions = self._positions + 1
        # copy: np.asarray over a device buffer is read-only,
        # and _admit writes per-slot entries in place
        self._last_tok = np.array(tok, np.int32)
        for s in occ:
            self._emit(s, int(tok[s]))

    def _pop_pending(self) -> _GenRequest:
        """Priority-ordered dequeue with bounded aging (caller holds
        ``_cond``, ``_pending`` non-empty).  Effective rank =
        ``max(0, rank - waited // aging_s)`` — a batch request climbs
        one priority class per ``aging_s`` seconds queued, so it
        cannot starve forever behind a sustained interactive stream;
        ties break FIFO by queue position.  ``aging_s=0`` disables
        aging (strict priority)."""
        aging = self._aging_s
        now = time.monotonic() if aging > 0 else 0.0
        best_i, best_key = 0, None
        for i, r in enumerate(self._pending):
            eff = r.priority
            if aging > 0:
                eff = max(0, eff - int((now - r.t_submit) / aging))
            key = (eff, i)
            if best_key is None or key < best_key:
                best_i, best_key = i, key
        req = self._pending[best_i]
        del self._pending[best_i]
        return req

    def _admit(self):
        """Token-boundary admission: move queued requests into free
        slots, grouped per prompt-length bucket, one masked prefill per
        group; running neighbours' cache rows are untouched."""
        took: List[Tuple[int, _GenRequest]] = []
        with self._cond:
            if self._paused:
                return
            free = [i for i, r in enumerate(self._slot_req)
                    if r is None]
            while self._pending and free:
                req = self._pop_pending()
                self._admission.release()
                if req.expired():
                    self._shed(req)
                    continue
                if req.cancelled:
                    self._retire(req, slot=None)
                    continue
                took.append((free.pop(0), req))
        if not took:
            return
        groups: Dict[int, List[Tuple[int, _GenRequest]]] = {}
        for slot, req in took:
            pb = self.session.prompt_bucket(len(req.prompt))
            groups.setdefault(pb, []).append((slot, req))
        for pb, members in sorted(groups.items()):
            S = self.slots
            ids = np.zeros((S, pb), np.int32)
            plens = np.ones((S,), np.int32)
            mask = np.zeros((S,), bool)
            for slot, req in members:
                n = len(req.prompt)
                ids[slot, :n] = req.prompt
                plens[slot] = n
                mask[slot] = True
                self._slot_req[slot] = req
                self._keys[slot] = np.asarray(
                    jax_random_key(req.seed), np.uint32)
                self._temps[slot] = req.temperature
                self._tks[slot] = req.top_k
                self._tps[slot] = req.top_p
                self._note_slot_admit(slot, req)
            t0 = _tracer.now_ns() if _rtrace.active else 0
            tok, self._caches = self.session.prefill(
                self._caches, ids, plens, mask, self._keys,
                self._temps, self._tks, self._tps)
            if t0:
                self._trace_boundary(
                    "prefill", t0, _tracer.now_ns(),
                    [s for s, _r in members], bucket=pb)
            for slot, req in members:
                self._positions[slot] = plens[slot]
                self._last_tok[slot] = tok[slot]
                self._emit(slot, int(tok[slot]))

    def _note_slot_admit(self, slot: int, req: _GenRequest):
        """Queue-wait span closes + flight slot-admit event for one
        request entering a decode slot."""
        if req.ctx is not None and _rtrace.active:
            req.ctx.record("queue_wait", req.t_submit_ns, slot=slot)
        if _flight.active:
            _flight.note("serve", "slot_admit",
                         engine=self.metrics_prefix, slot=slot,
                         request=req.request_id,
                         prompt=int(req.prompt.size))

    def _emit(self, slot: int, tok: int):
        req = self._slot_req[slot]
        if req is None:
            return
        now = time.monotonic()
        with self._mlock:
            if req.t_first is None:
                req.t_first = now
                self._m_ttft.observe((now - req.t_submit) * 1e3)
            else:
                self._m_itl.observe((now - req.t_last) * 1e3)
        req.t_last = now
        req.tokens.append(tok)
        req.queue.put(tok)
        hit_eos = req.eos is not None and tok == int(req.eos)
        out_of_room = self._positions[slot] + 1 >= self.max_length
        budget_done = len(req.tokens) >= req.max_new
        if hit_eos or req.cancelled or out_of_room or budget_done:
            req.finish_reason = (
                "cancelled" if req.cancelled else
                "eos" if hit_eos else
                "cache_full" if out_of_room and not budget_done else
                "max_new_tokens")
            self._retire(req, slot)

    def _release_resources(self, req: _GenRequest):
        """THE accounting seam: every way a request leaves the engine
        (finish, cancel, deadline shed, kv-block shed, engine failure)
        returns its reservations through this one method — token budget
        here, plus KV block references in the paged override.  One
        place to audit means no path can leak."""
        self._admission.release_tokens(req.budget)

    def _note_tenant(self, req: _GenRequest, what: str, n: int = 1,
                     latency_ms: Optional[float] = None):
        """One bump on a ``<prefix>.tenant.<t>.*`` accounting series —
        the tenant-labeled counters a fleet ``/metrics`` aggregation
        sums across replicas.  No-op for untenanted requests.  Callers
        must NOT hold ``_mlock``."""
        if req.tenant is None:
            return
        from ..profiler import metrics as _metrics
        base = f"{self.metrics_prefix}.tenant.{req.tenant}"
        with self._mlock:
            _metrics.counter(
                f"{base}.{what}",
                f"per-tenant {what} (requests or tokens)").inc(n)
            if latency_ms is not None:
                _metrics.histogram(
                    f"{base}.latency_ms",
                    "per-tenant end-to-end request latency"
                    ).observe(latency_ms)

    def _retire(self, req: _GenRequest, slot: Optional[int]):
        if slot is not None:
            self._slot_req[slot] = None
        self._release_resources(req)
        reason = req.finish_reason or \
            ("cancelled" if req.cancelled else "done")
        if _flight.active:
            _flight.note("serve", "slot_retire",
                         engine=self.metrics_prefix, slot=slot,
                         request=req.request_id, reason=reason,
                         tokens=len(req.tokens))
        if not req.future.done():
            req.future.set_result(np.asarray(req.tokens, np.int32))
            with self._mlock:
                if req.cancelled:
                    self._m_cancelled.inc()
                else:
                    self._m_done.inc()
            if not req.cancelled:
                self._note_tenant(
                    req, "completed",
                    latency_ms=(time.monotonic() - req.t_submit) * 1e3)
                self._note_tenant(req, "tokens_out", len(req.tokens))
        req.queue.put(None)

    def _shed(self, req: _GenRequest):
        with self._mlock:
            self._admission.shed_deadline()
        self._note_tenant(req, "shed")
        self._release_resources(req)
        if req.ctx is not None and _rtrace.active:
            req.ctx.record("queue_wait", req.t_submit_ns,
                           outcome="shed_deadline", terminated=True)
        exc = DeadlineExceeded(
            "request deadline expired while queued (engine overloaded "
            "relative to the deadline)")
        if not req.future.done():
            req.future.set_exception(exc)
        req.queue.put(exc)

    def _fail_all(self, exc: BaseException):
        with self._cond:
            pending = list(self._pending)
            self._pending.clear()
            parked, self._parked = list(self._parked), []
        for r in parked:
            r.parked = None   # drop the host-side swap payload
        victims = pending + parked + \
            [r for r in self._slot_req if r is not None]
        self._slot_req = [None] * self.slots
        if _memscope.active and _memscope.is_oom(exc):
            # OOM forensics before the generic failure dump: census +
            # pool occupancy + the flight ring, then victims fail with
            # the original error exactly as before
            _memscope.oom_dump(
                exc, context=f"engine:{self.metrics_prefix}",
                pool=getattr(self, "pool", None),
                prefix_cache=getattr(self, "prefix_cache", None))
        if _flight.active:
            _flight.note("serve", "engine_failure",
                         engine=self.metrics_prefix,
                         error=f"{type(exc).__name__}: {exc}",
                         victims=len(victims))
            # post-mortem artifact: the last N things this engine did,
            # written next to the gang's other dumps when
            # PADDLE_FLIGHT_DIR is configured
            _flight.dump(reason="engine-failure")
        for req in victims:
            self._release_resources(req)
            if req.ctx is not None and _rtrace.active:
                req.ctx.record("failed", req.t_submit_ns,
                               outcome=type(exc).__name__,
                               terminated=True)
            if not req.future.done():
                req.future.set_exception(exc)
                with self._mlock:
                    self._m_failed.inc()
            req.queue.put(exc)
        for _ in pending:
            self._admission.release()


def jax_random_key(seed: int):
    """Per-request base PRNG key — derived from the request's OWN seed
    so its sampled stream is independent of slot placement and
    batchmates (the decode-gate parity contract)."""
    import jax
    return np.asarray(jax.random.PRNGKey(int(seed)), np.uint32)


# ---------------------------------------------------------------------------
# paged-KV serving memory: block-pool continuous batching
# ---------------------------------------------------------------------------

class PagedGenerationEngine(GenerationEngine):
    """:class:`GenerationEngine` over the paged KV-cache subsystem
    (``paddle_tpu/generation/paged_kv.py``): same continuous-batching
    scheduler, same client surface, but KV memory is a shared
    refcounted block pool instead of one worst-case ``(max_length, H,
    D)`` buffer per slot.

    What changes operationally:

    - **admission** switches from the worst-case token budget to live
      block-pool occupancy: ``submit`` reserves nothing (the
      ``<name>.kv.blocks_in_flight`` gauge replaces
      ``tokens_in_flight`` as the admission signal), blocks are
      allocated lazily as each request actually grows, and a pool that
      cannot supply a block sheds the request with a typed
      ``RequestRejected(reason="kv_blocks")`` — never a corrupted
      batch (the ``kv.block_alloc`` chaos site injects exactly this);
    - **prefix cache**: prompts sharing a prefix with any earlier
      prompt (sha256 content-addressed, ``prefix_cache_blocks`` cap)
      skip straight to a chunked prefill of the uncached suffix —
      shared system prompts prefill once; partially shared blocks are
      copied-on-write before a request appends into them;
    - **int8 KV** (``kv_cache_dtype='int8'``): blocks stored int8 with
      per-token-per-head scales, dequantized inside the attention
      executable — ~3.6x less HBM per block (k+v int8 plus two f32
      per-token-per-head scale planes; 4096 -> 1152 bytes/token on
      the bench config), tolerance-level numerics;
    - **speculative decoding** (``speculative_k > 0``): the n-gram
      prompt-lookup drafter proposes up to k tokens per boundary and
      ONE batched verify executable commits the longest agreeing
      prefix — streams stay bit-identical to non-speculative decode
      (greedy and sampled; the drafter only changes how many forwards
      produce them).  ``<name>.spec.proposed`` / ``.accepted``
      counters and the ``.accept_rate`` gauge account it.

    Executable population stays bounded exactly like the contiguous
    engine: block tables and pool state are step *data*, never part of
    a compile key — one chunk executable per pow2 suffix bucket, one
    width-1 decode, one verify width, one block-copy helper.

    With ``block_size`` dividing ``max_length``, paged greedy decode
    is bit-exact against the contiguous PR 6 references
    (``tools/paged_gate.py`` pins it under chaos).
    """

    # -- construction hooks -------------------------------------------
    def _make_session(self, model, cfg: GenerationEngineConfig,
                      max_len: int):
        from ..generation import PagedGenerationSession
        return PagedGenerationSession(
            model, batch_capacity=cfg.max_slots, max_length=max_len,
            block_size=cfg.block_size, num_blocks=cfg.num_blocks,
            kv_dtype=cfg.kv_cache_dtype,
            prompt_bucket_min=cfg.prompt_bucket_min, name=cfg.name)

    def _make_admission(self, cfg: GenerationEngineConfig
                        ) -> AdmissionController:
        # no token budget: paged admission is queue depth at submit
        # plus live block-pool occupancy at allocation time
        return AdmissionController(
            cfg.max_queue, max_rows=None, name=cfg.name,
            max_tokens=None, quotas=self._make_quotas(cfg))

    def _token_reservation(self, prompt, max_new: int) -> int:
        return 0

    def _init_slot_state(self):
        from ..generation import BlockPool, PrefixCache
        cfg = self.config
        ses = self.session
        self._init_slot_arrays()
        self._arenas = ses.init_arenas()
        self._table = np.full((self.slots, ses.blocks_per_slot), -1,
                              np.int32)
        self.pool = BlockPool(ses.num_blocks, ses.block_size,
                              name=cfg.name)
        self.pool.block_bytes = ses.arena_bytes_per_block()
        self.prefix_cache = PrefixCache(
            self.pool, cfg.prefix_cache_blocks, name=cfg.name)
        self.speculative_k = max(int(cfg.speculative_k), 0)
        from ..profiler import metrics as _metrics
        p = cfg.name
        self._m_spec_proposed = _metrics.counter(
            f"{p}.spec.proposed", "draft tokens proposed by the "
            "prompt-lookup drafter")
        self._m_spec_accepted = _metrics.counter(
            f"{p}.spec.accepted", "draft tokens the verify step "
            "accepted (each one a forward pass saved)")
        self._g_spec_rate = _metrics.gauge(
            f"{p}.spec.accept_rate", "accepted/proposed draft ratio "
            "(engine lifetime)")
        self._m_preempted = _metrics.counter(
            f"{p}.request.preempted", "decode slots preempted to host "
            "memory under block-pool pressure")
        self._m_resumed = _metrics.counter(
            f"{p}.request.resumed", "preempted requests swapped back "
            "into a decode slot")
        self._g_parked = _metrics.gauge(
            f"{p}.requests_parked", "requests currently swapped out "
            "to host memory awaiting blocks")
        if _memscope.active:
            self._note_memory_tags()

    def _kv_arena_bytes(self) -> int:
        # paged: the pre-allocated arena, not the live-array walk
        return int(self.pool.num_blocks) * \
            int(getattr(self.pool, "block_bytes", 0))

    def memory_breakdown(self) -> Dict[str, int]:
        out = super().memory_breakdown()
        out["mem_prefix_cache_bytes"] = \
            len(self.prefix_cache) * \
            int(getattr(self.pool, "block_bytes", 0))
        return out

    def _warmup(self):
        """Every chunk-width executable (one per pow2 suffix bucket +
        the width-1 decode + the verify width when speculative is
        armed) compiled over throwaway arenas with all-zero feeds —
        every write is dropped by the table, so warmup is
        mathematically inert and peak memory stays one arena set."""
        from .bucketing import seq_buckets
        ses = self.session
        S = self.slots
        keys = np.zeros((S, 2), np.uint32)
        temps = np.zeros((S,), np.float32)
        tks = np.zeros((S,), np.int32)
        tps = np.ones((S,), np.float32)
        zeros = np.zeros((S,), np.int32)
        arenas = ses.init_arenas()
        table = np.full((S, ses.blocks_per_slot), -1, np.int32)
        errors = []
        for pb in seq_buckets(self.max_length,
                              self.config.prompt_bucket_min):
            try:
                _tok, arenas = ses.prefill(
                    arenas, table, np.zeros((S, pb), np.int32), zeros,
                    zeros, keys, temps, tks, tps, live_rows=0)
            except Exception as e:  # noqa: BLE001 — best-effort, but loud
                errors.append((f"pchunk:{pb}", e))
        try:
            ses.decode(arenas, table, zeros, zeros, keys, temps, tks,
                       tps, live_rows=0)
        except Exception as e:      # noqa: BLE001
            errors.append(("pchunk:1", e))
        if self.config.speculative_k > 0:
            W = int(self.config.speculative_k) + 1
            try:
                ses.verify(arenas, table, np.zeros((S, W), np.int32),
                           zeros, zeros, keys, temps, tks, tps,
                           live_rows=0)
            except Exception as e:  # noqa: BLE001
                errors.append((f"pverify:{W}", e))
        self._finish_warmup(errors)

    # -- block accounting ---------------------------------------------
    def _release_resources(self, req: _GenRequest):
        """The accounting seam, paged edition: token budget (a no-op —
        paged submit reserves none) AND every KV block reference the
        request holds, in one place."""
        super()._release_resources(req)
        if req.blocks:
            self.pool.decref(req.blocks)
            req.blocks = []

    # -- disaggregated KV transfer (serving/disagg.py drives these) ----
    def export_prefix_chain(self, tokens) -> Optional[bytes]:
        """Serialize this engine's longest cached prefix chain for
        ``tokens`` into a ``kv_wire`` blob (``None`` on cache miss) —
        the prefill side of disaggregated serving.

        Thread-safe from any thread: ``lookup`` transfers pool
        references that pin the chain for the duration, the cached
        blocks are immutable by the copy-on-write discipline (a writer
        always copies a shared block first), and the arena gather is
        pure — a concurrent decode round can replace ``self._arenas``
        without invalidating the snapshot this reads."""
        from ..generation import kv_wire
        toks = np.ascontiguousarray(tokens, dtype=np.int32).reshape(-1)
        chain, covered = self.prefix_cache.lookup(toks)
        if not chain:
            return None
        try:
            payload = self.session.swap_out_blocks(self._arenas, chain)
            return kv_wire.serialize_chain(
                toks[:covered], covered, self.session.block_size,
                payload)
        finally:
            self.pool.decref(chain)

    def import_prefix_chain(self, blob: bytes,
                            timeout: Optional[float] = 300.0) -> int:
        """Verify a ``kv_wire`` blob, allocate blocks for it, and hand
        it to the scheduler thread to swap into the arenas and insert
        into the prefix cache at the next token boundary — the decode
        side of disaggregated serving.  Returns the covered token
        count; subsequent submits of a prompt sharing the prefix hit
        the cache exactly as if this engine had prefilled it.

        Raises :class:`~..generation.kv_wire.KVTransferCorrupt`
        (counted, zero unverified bytes adopted) on a bad blob,
        ``BlockPoolExhausted`` when the pool cannot hold the chain,
        and :class:`EngineClosed` on a closed/stopping engine —
        in every case the caller simply decodes without the shipment
        (a local re-prefill), never over suspect KV."""
        from ..generation import blocks_for_tokens, kv_wire
        doc = kv_wire.deserialize_chain(
            blob, expect_block_size=self.session.block_size,
            expect_spec=self.session.block_spec(self._arenas))
        blocks = self.pool.alloc(blocks_for_tokens(
            doc["covered"], self.session.block_size))
        imp = {"tokens": doc["tokens"], "covered": doc["covered"],
               "blocks": blocks, "payload": doc["payload"],
               "done": threading.Event(), "error": None}
        with self._cond:
            if self._closed or self._stop:
                self.pool.decref(blocks)
                raise EngineClosed("generation engine is closed")
            self._imports.append(imp)
            self._cond.notify_all()
        if not imp["done"].wait(timeout):
            # leave the entry queued: the scheduler still owns applying
            # it and the decref that balances the alloc above
            raise TimeoutError("KV chain import timed out")
        if imp["error"] is not None:
            raise imp["error"]
        return imp["covered"]

    def _apply_imports(self):
        """Adopt queued shipped-in chains (scheduler thread, token
        boundary): ``device_put`` each payload into its pre-allocated
        blocks, then offer the chain to the prefix cache (which takes
        its own references).  The import's alloc-time hold is released
        either way, so retained blocks end cache-owned at refcount 1
        and already-cached duplicates free immediately.  A failing
        import faults only its caller, never the engine."""
        while True:
            with self._cond:
                if not self._imports:
                    return
                imp = self._imports.pop(0)
            try:
                self._arenas = self.session.swap_in_blocks(
                    self._arenas, imp["blocks"], imp["payload"])
                self.prefix_cache.insert(imp["tokens"], imp["blocks"])
                if _flight.active:
                    _flight.note("kv", "chain_import",
                                 engine=self.metrics_prefix,
                                 covered=int(imp["covered"]),
                                 blocks=len(imp["blocks"]))
            except BaseException as e:  # noqa: BLE001 — fault the importer only
                imp["error"] = e
            finally:
                self.pool.decref(imp["blocks"])
                imp["done"].set()

    def _drain_imports(self, exc: BaseException):
        """Fail every queued import (engine close / loop death):
        release the alloc-time holds and wake the waiting callers."""
        with self._cond:
            imps, self._imports = list(self._imports), []
        for imp in imps:
            self.pool.decref(imp["blocks"])
            imp["error"] = exc
            imp["done"].set()

    def _prepare_slot(self, slot: int, req: _GenRequest):
        """Prefix-cache lookup + block allocation + copy-on-write for
        one admitted request; fills the slot's table row.  Returns the
        request's COW ``(src, dst)`` block pair (or ``None``) instead
        of dispatching the copy — the caller batches all pairs of one
        admission round into a single ``copy_blocks`` call, so shared
        partial-tail prefixes cost one launch per ``batch_capacity``
        copies, not one per request.  Raises
        :class:`BlockPoolExhausted` with every transferred reference
        returned (the caller sheds typed)."""
        from ..generation import BlockPoolExhausted, blocks_for_tokens
        ses = self.session
        bs = ses.block_size
        plen = int(req.prompt.size)
        chain, cached_len = self.prefix_cache.lookup(req.prompt)
        # always re-feed >= 1 token: the chunk executable samples the
        # token AFTER each row's window, so a fully-cached prompt still
        # feeds its last token (writing bit-identical k/v into a COW
        # copy of the tail block)
        cached = min(cached_len, plen - 1)
        fb = cached // bs               # first block this row writes
        total = blocks_for_tokens(plen, bs)
        # bind the ambient request identity across the allocation so
        # the pool's kv.exhausted flight event carries request_id
        if _rtrace.active and req.ctx is not None:
            _rtrace.set_current(req.ctx)
        try:
            fresh = self.pool.alloc(total - fb)
        except BlockPoolExhausted:
            if chain:
                self.pool.decref(chain)
            raise
        finally:
            if _rtrace.active:
                _rtrace.set_current(None)
        row = chain[:fb] + fresh
        cow = None
        if fb < len(chain):
            # the write window starts inside a shared cached block:
            # copy it into this row's first fresh block, return the
            # shared holds we no longer use
            cow = (chain[fb], fresh[0])
            self.pool.decref(chain[fb:])
        req.blocks = row
        req.cached_len = cached
        self._table[slot, :] = -1
        self._table[slot, :len(row)] = row
        self._slot_req[slot] = req
        self._keys[slot] = np.asarray(jax_random_key(req.seed),
                                      np.uint32)
        self._temps[slot] = req.temperature
        self._tks[slot] = req.top_k
        self._tps[slot] = req.top_p
        return cow

    def _ensure_blocks(self, slot: int, req: _GenRequest,
                       upto_pos: int):
        """Grow the slot's table to cover writes through absolute
        position ``upto_pos`` (lazy decode-time growth — the admission
        win over worst-case reservation).  Raises
        :class:`BlockPoolExhausted`."""
        from ..generation import blocks_for_tokens
        need = blocks_for_tokens(int(upto_pos) + 1,
                                 self.session.block_size)
        have = len(req.blocks)
        if need <= have:
            return
        if _rtrace.active and req.ctx is not None:
            _rtrace.set_current(req.ctx)
        try:
            fresh = self.pool.alloc(need - have)
        finally:
            if _rtrace.active:
                _rtrace.set_current(None)
        req.blocks.extend(fresh)
        self._table[slot, have:have + len(fresh)] = fresh

    def _shed_kv(self, req: _GenRequest, slot: Optional[int], cause):
        """Pool exhaustion (organic or ``kv.block_alloc``-injected):
        shed the request with the typed error — the live batch never
        sees a partial allocation."""
        with self._mlock:
            self._admission.shed_kv_blocks()
        if slot is not None:
            self._slot_req[slot] = None
            self._table[slot, :] = -1
        if _memscope.active:
            # exhaustion forensics even though the shed is graceful:
            # the dump says WHAT filled the pool when capacity planning
            # asks later (one artifact per process; the flight event
            # fires every time)
            _memscope.oom_dump(
                cause if isinstance(cause, BaseException)
                else RuntimeError(str(cause)),
                context=f"kv_shed:{self.metrics_prefix}",
                pool=self.pool, prefix_cache=self.prefix_cache)
        if _flight.active:
            _flight.note("serve", "kv_shed",
                         engine=self.metrics_prefix, slot=slot,
                         request=req.request_id, cause=str(cause))
        if req.ctx is not None and _rtrace.active:
            req.ctx.record("shed", req.t_submit_ns,
                           outcome="kv_blocks", terminated=True)
        self._release_resources(req)
        exc = RequestRejected(
            f"paged KV block pool exhausted ({cause}); request shed — "
            "retry when running generations free blocks, or provision "
            "more num_blocks", reason="kv_blocks")
        if not req.future.done():
            req.future.set_exception(exc)
        req.queue.put(exc)

    # -- preemption to host memory ------------------------------------
    def _pick_victim(self, max_rank: int,
                     exclude: Optional[int] = None) -> Optional[int]:
        """Choose the live slot to preempt for a rank-``max_rank``
        requester: strictly lower priority only (batch never bumps
        batch), preferring the lowest class first, then the slot
        holding the most blocks (one swap frees the most memory), then
        the lowest slot index (determinism — the gates assert exact
        preempt counts)."""
        best, best_key = None, None
        for s, r in enumerate(self._slot_req):
            if r is None or s == exclude or r.priority <= max_rank:
                continue
            if not r.tokens:
                # placed this round but not yet prefilled: the slot's
                # position and KV bytes are not valid swap state
                continue
            key = (-r.priority, -len(r.blocks), s)
            if best_key is None or key < best_key:
                best, best_key = s, key
        return best

    def _preempt_for(self, req: _GenRequest,
                     exclude: Optional[int] = None) -> bool:
        """Free blocks for ``req`` by preempting one strictly
        lower-priority live slot to host memory.  Returns False when
        no eligible victim exists (the caller sheds the requester
        typed instead — preemption never bumps an equal-or-higher
        class)."""
        victim = self._pick_victim(req.priority, exclude=exclude)
        if victim is None:
            return False
        self._preempt_slot(victim)
        return True

    def _preempt_slot(self, slot: int):
        """Swap one live decode slot out to host memory: gather its
        blocks' contents (pinned host memory when the backend has the
        ``pinned_host`` kind; plain numpy on CPU CI), park the request
        with its sampling state, and free the blocks through
        ``_release_resources``.  The parked stream resumes bit-exact:
        the per-step sample key is ``fold_in(base_key, position)`` and
        both position and KV bytes are restored verbatim, so the
        continuation is the very token sequence an unpreempted run
        would have produced."""
        req = self._slot_req[slot]
        from ..utils import chaos as _chaos
        if _chaos.active:
            try:
                _chaos.hit("serve.preempt")
            except Exception as e:  # noqa: BLE001 — injected swap fail
                # a failed swap-out must not corrupt the batch: shed
                # the victim typed (blocks still freed) instead of
                # parking state we could not capture
                self._shed_kv(req, slot, e)
                return
        nblocks = len(req.blocks)
        pos = int(self._positions[slot])
        host = self.session.swap_out_blocks(self._arenas, req.blocks)
        req.parked = {"host": host, "nblocks": nblocks, "pos": pos,
                      "last": int(self._last_tok[slot])}
        self._slot_req[slot] = None
        self._table[slot, :] = -1
        self._release_resources(req)     # returns the device blocks
        with self._cond:
            self._parked.append(req)
        with self._mlock:
            self._m_preempted.inc()
            self._g_parked.set(len(self._parked))
        self._note_tenant(req, "preempted")
        if _flight.active:
            _flight.note("serve", "preempt",
                         engine=self.metrics_prefix, slot=slot,
                         request=req.request_id, tenant=req.tenant,
                         priority=req.priority_name, blocks=nblocks,
                         position=pos)
        if req.ctx is not None and _rtrace.active:
            req.ctx.record("preempt", _tracer.now_ns(), slot=slot,
                           blocks=nblocks)

    def _sweep_parked(self):
        """Deadline pass over the parked set: a stream whose deadline
        expired (or was cancelled) while swapped out sheds typed
        ``deadline_preempted`` — resuming it would burn blocks on a
        stream nobody is waiting for."""
        with self._cond:
            dead = [r for r in self._parked
                    if r.expired() or r.cancelled]
            for r in dead:
                self._parked.remove(r)
        for req in dead:
            req.parked = None            # release the host-side state
            if req.cancelled:
                self._retire(req, slot=None)
                continue
            with self._mlock:
                self._admission.shed_deadline(preempted=True)
                self._g_parked.set(len(self._parked))
            self._note_tenant(req, "shed")
            self._release_resources(req)
            if req.ctx is not None and _rtrace.active:
                req.ctx.record("shed", req.t_submit_ns,
                               outcome="deadline_preempted",
                               terminated=True)
            exc = DeadlineExceeded(
                "request deadline expired while preempted to host "
                "memory", reason="deadline_preempted")
            if not req.future.done():
                req.future.set_exception(exc)
            req.queue.put(exc)

    def _try_resume(self):
        """Admission-tail resume pass: swap parked requests back into
        free slots as the pool refills, highest aged priority first.
        A pool that cannot cover a parked stream while other slots are
        live simply waits (their retirements will free blocks); with
        nothing live it drops the prefix cache's holds and, if the
        stream still cannot fit, sheds it typed rather than wedging
        the scheduler."""
        from ..generation import BlockPoolExhausted
        cleared_cache = False
        while True:
            with self._cond:
                if not self._parked or self._paused:
                    return
                free = [i for i, r in enumerate(self._slot_req)
                        if r is None]
                if not free:
                    return
                aging = self._aging_s
                now = time.monotonic()

                def _eff(r):
                    e = r.priority
                    if aging > 0:
                        e = max(0, e - int((now - r.t_submit) / aging))
                    return e

                req = min(self._parked,
                          key=lambda r: (_eff(r), r.t_submit))
                slot = free[0]
            try:
                blocks = self.pool.alloc(req.parked["nblocks"])
            except BlockPoolExhausted as e:
                if self._occupied():
                    return       # retirements will free blocks
                if not cleared_cache:
                    self.prefix_cache.clear()
                    cleared_cache = True
                    continue
                # the pool physically cannot hold this stream even
                # empty: shed typed rather than park forever
                with self._cond:
                    self._parked.remove(req)
                with self._mlock:
                    self._g_parked.set(len(self._parked))
                req.parked = None
                self._shed_kv(req, None, e)
                continue
            self._resume_into(slot, req, blocks)

    def _resume_into(self, slot: int, req: _GenRequest,
                     blocks: List[int]):
        """Swap a parked request back in: ``device_put`` the host
        payload into the fresh ``blocks``, rewrite the slot's table
        row, restore position/last-token/sampling params.  Block ids
        may differ from the preempted set — the table rewrite absorbs
        that; contents are bit-identical."""
        park = req.parked
        self._arenas = self.session.swap_in_blocks(
            self._arenas, blocks, park["host"])
        req.blocks = blocks
        req.parked = None
        with self._cond:
            self._parked.remove(req)
        self._table[slot, :] = -1
        self._table[slot, :len(blocks)] = blocks
        self._slot_req[slot] = req
        self._positions[slot] = park["pos"]
        self._last_tok[slot] = park["last"]
        self._keys[slot] = np.asarray(jax_random_key(req.seed),
                                      np.uint32)
        self._temps[slot] = req.temperature
        self._tks[slot] = req.top_k
        self._tps[slot] = req.top_p
        with self._mlock:
            self._m_resumed.inc()
            self._g_parked.set(len(self._parked))
        self._note_tenant(req, "resumed")
        if _flight.active:
            _flight.note("serve", "resume",
                         engine=self.metrics_prefix, slot=slot,
                         request=req.request_id, tenant=req.tenant,
                         priority=req.priority_name,
                         blocks=len(blocks), position=park["pos"])
        if req.ctx is not None and _rtrace.active:
            req.ctx.record("resume", _tracer.now_ns(), slot=slot,
                           blocks=len(blocks))

    def _retire(self, req: _GenRequest, slot: Optional[int]):
        if slot is not None:
            self._table[slot, :] = -1
        super()._retire(req, slot)

    def _fail_all(self, exc: BaseException):
        super()._fail_all(exc)
        self._drain_imports(exc)
        self._table[:, :] = -1
        with self._mlock:
            self._g_parked.set(0)

    def close(self, timeout: Optional[float] = 60.0):
        super().close(timeout=timeout)
        # imports stranded by the loop's exit fail typed (their alloc
        # holds release here), THEN the cache lets go — so the pool
        # drains to all-free (the leak canary in the tests)
        self._drain_imports(EngineClosed("generation engine is closed"))
        self.prefix_cache.clear()

    # -- scheduler overrides ------------------------------------------
    def _admit(self):
        """Token-boundary admission, paged edition: adopt shipped-in
        KV chains, deadline-sweep the parked set, admit queued
        requests (preempting lower-priority slots under pool
        pressure), then resume parked streams into whatever slots and
        blocks remain."""
        self._apply_imports()
        self._sweep_parked()
        self._admit_pending()
        self._try_resume()

    def _admit_pending(self):
        """Queued-request admission: prefix-cache lookup + block
        allocation per request, then ONE chunked prefill per
        suffix-length bucket feeding each row's uncached suffix at its
        true offset.  Pool exhaustion preempts the lowest strictly
        lower-priority live slot and retries; with no eligible victim
        the *incoming* request sheds typed."""
        from ..generation import BlockPoolExhausted, blocks_for_tokens
        took: List[Tuple[int, _GenRequest]] = []
        with self._cond:
            if self._paused:
                return
            free = [i for i, r in enumerate(self._slot_req)
                    if r is None]
            while self._pending and free:
                req = self._pop_pending()
                self._admission.release()
                if req.expired():
                    self._shed(req)
                    continue
                if req.cancelled:
                    self._retire(req, slot=None)
                    continue
                took.append((free.pop(0), req))
        if not took:
            return
        placed: List[Tuple[int, _GenRequest]] = []
        cows: List[Tuple[int, int]] = []
        for slot, req in took:
            shed = False
            while True:
                try:
                    cow = self._prepare_slot(slot, req)
                    break
                except BlockPoolExhausted as e:
                    if self._preempt_for(req, exclude=slot):
                        continue     # blocks freed — retry the alloc
                    self._shed_kv(req, None, e)
                    shed = True
                    break
            if shed:
                continue
            if cow is not None:
                cows.append(cow)
            self._note_slot_admit(slot, req)
            placed.append((slot, req))
        if not placed:
            return
        if cows:
            # one batched copy-on-write launch for the whole round
            self._arenas = self.session.copy_blocks(
                self._arenas, [s for s, _ in cows],
                [d for _, d in cows])
        groups: Dict[int, List[Tuple[int, _GenRequest]]] = {}
        for slot, req in placed:
            flen = len(req.prompt) - req.cached_len
            groups.setdefault(self.session.prompt_bucket(flen),
                              []).append((slot, req))
        for pb, members in sorted(groups.items()):
            S = self.slots
            ids = np.zeros((S, pb), np.int32)
            starts = np.zeros((S,), np.int32)
            feed = np.zeros((S,), np.int32)
            for slot, req in members:
                suffix = req.prompt[req.cached_len:]
                ids[slot, :len(suffix)] = suffix
                starts[slot] = req.cached_len
                feed[slot] = len(suffix)
            t0 = _tracer.now_ns() if _rtrace.active else 0
            tok, self._arenas = self.session.prefill(
                self._arenas, self._table, ids, starts, feed,
                self._keys, self._temps, self._tks, self._tps,
                live_rows=len(members))
            if t0:
                self._trace_boundary(
                    "prefill", t0, _tracer.now_ns(),
                    [s for s, _r in members], bucket=pb)
            for slot, req in members:
                # offer the now-filled prompt blocks to the prefix
                # cache BEFORE emit (emit may retire the request,
                # releasing its holds)
                n = len(req.prompt)
                self.prefix_cache.insert(
                    req.prompt,
                    req.blocks[:blocks_for_tokens(
                        n, self.session.block_size)])
                self._positions[slot] = n
                self._last_tok[slot] = tok[slot]
                self._emit(slot, int(tok[slot]))

    def _decode_round(self, occ: List[int]):
        from ..generation import BlockPoolExhausted, draft_row
        k = self.speculative_k
        if k > 0:
            drafts: Dict[int, List[int]] = {}
            for s in occ:
                req = self._slot_req[s]
                ctx = np.concatenate(
                    [req.prompt, np.asarray(req.tokens, np.int32)])
                room = self.max_length - int(self._positions[s])
                drafts[s] = draft_row(ctx, k, room,
                                      ngram=self.config.spec_ngram)
            if any(drafts.values()):
                self._verify_round(occ, drafts, k)
                return
        # plain paged decode: each live row writes one token at its
        # position — grow its table lazily first; pool pressure
        # preempts a strictly lower-priority neighbour before shedding
        victims = []
        for s in occ:
            req = self._slot_req[s]
            if req is None:
                continue     # preempted for an earlier row this pass
            while True:
                try:
                    self._ensure_blocks(s, req,
                                        int(self._positions[s]))
                    break
                except BlockPoolExhausted as e:
                    if self._preempt_for(req, exclude=s):
                        continue
                    victims.append((s, req, e))
                    break
        for s, req, e in victims:
            self._shed_kv(req, s, e)
        occ = self._occupied()
        if not occ:
            return
        t0 = _tracer.now_ns() if _rtrace.active else 0
        tok, self._arenas = self.session.decode(
            self._arenas, self._table, self._last_tok,
            self._positions, self._keys, self._temps, self._tks,
            self._tps, live_rows=len(occ))
        if t0:
            self._trace_boundary("decode", t0, _tracer.now_ns(), occ,
                                 occupancy=len(occ))
        with self._mlock:
            self._m_occ.observe(len(occ))
        self._positions = self._positions + 1
        self._last_tok = np.array(tok, np.int32)
        for s in occ:
            self._emit(s, int(tok[s]))

    def _verify_round(self, occ: List[int],
                      drafts: Dict[int, List[int]], k: int):
        """Speculative boundary: one batched verify at width k+1;
        each row commits the longest prefix of its drafts the model's
        own sampler agrees with, plus the correction token — the
        committed stream is exactly what sequential decode would have
        produced."""
        from ..generation import (BlockPoolExhausted, accept_span,
                                  fill_verify_row)
        W = k + 1
        S = self.slots
        ids = np.zeros((S, W), np.int32)
        feed = np.zeros((S,), np.int32)
        victims, live = [], []
        for s in occ:
            req = self._slot_req[s]
            if req is None:
                continue     # preempted for an earlier row this pass
            d = drafts.get(s) or []
            fill_verify_row(ids, feed, s, int(self._last_tok[s]), d)
            shed = False
            while True:
                try:
                    self._ensure_blocks(
                        s, req, int(self._positions[s]) + len(d))
                    break
                except BlockPoolExhausted as e:
                    if self._preempt_for(req, exclude=s):
                        continue
                    feed[s] = 0          # shed row stays inert
                    victims.append((s, req, e))
                    shed = True
                    break
            if not shed:
                live.append(s)
        for s, req, e in victims:
            self._shed_kv(req, s, e)
        # a later row's preemption may have parked an earlier live row:
        # its writes drop through the -1 table, but keep it out of the
        # feed and the live count
        live2 = []
        for s in live:
            if self._slot_req[s] is None:
                feed[s] = 0
            else:
                live2.append(s)
        live = live2
        if not live:
            return
        t0 = _tracer.now_ns() if _rtrace.active else 0
        toks, self._arenas = self.session.verify(
            self._arenas, self._table, ids, self._positions, feed,
            self._keys, self._temps, self._tks, self._tps,
            live_rows=len(live))
        if t0:
            # speculative boundary: the verify step IS this round's
            # decode work — one fused span, every live row linked
            self._trace_boundary("decode", t0, _tracer.now_ns(), live,
                                 occupancy=len(live), verify_width=W)
        with self._mlock:
            self._m_occ.observe(len(live))
        proposed = sum(len(drafts.get(s) or []) for s in live)
        accepted = 0
        for s in live:
            span = accept_span(drafts.get(s) or [], toks[s])
            for j, t in enumerate(span):
                self._positions[s] += 1
                self._last_tok[s] = int(t)
                self._emit(s, int(t))
                # count only drafts that actually committed (span[-1]
                # is the correction/bonus token, not a draft; a row
                # retiring mid-span discards the rest)
                if j < len(span) - 1:
                    accepted += 1
                if self._slot_req[s] is None:
                    break               # retired mid-span (eos/budget)
        with self._mlock:
            self._m_spec_proposed.inc(proposed)
            self._m_spec_accepted.inc(accepted)
            total = self._m_spec_proposed.value
            self._g_spec_rate.set(
                (self._m_spec_accepted.value / total) if total else 0.0)

"""Admission control for the serving engine: shed, don't OOM.

An overloaded accelerator endpoint has exactly three honest choices:
queue (bounded!), reject explicitly, or fall over.  This module owns
the first two.  Every request passes :meth:`AdmissionController.admit`
before it may enter the engine queue:

- queue depth beyond ``max_queue``  -> :class:`RequestRejected`
  (``queue_full``) — the client sees backpressure immediately instead
  of a timeout after unbounded buffering;
- request rows beyond ``max_batch_size`` -> :class:`RequestRejected`
  (``too_large``) — a request the batcher could never place;
- engine closed -> :class:`RequestRejected` (``closed``).

Per-request deadlines produce :class:`DeadlineExceeded` when a request
expires while still queued (the batcher sheds it without running) or
when the client-side wait runs out.  All outcomes are SLO-accounted in
the metrics registry: ``serving.request.admitted``,
``serving.request.rejected[.reason]``, ``serving.request.shed_deadline``,
``serving.queue_depth``.

Multi-tenant SLO serving (PR 18) adds three pieces on top:

- **priority classes** (:data:`PRIORITIES` = interactive / standard /
  batch): requests carry a class, the generation engines dequeue in
  priority order with bounded aging (a queued request gains one class
  per ``aging_s`` waited, so batch traffic cannot starve forever);
- **per-tenant token buckets** (:class:`TenantQuotaTable`): quota
  exhaustion is a typed ``RequestRejected(reason="tenant_quota")``
  shed, hot-reloadable from a JSON file without a restart
  (:class:`QuotaWatcher` — the WeightWatcher pattern applied to
  config);
- **drain-rate Retry-After** (:class:`DrainRateEstimator`): every 429
  carries a ``retry_after`` derived from the observed queue drain
  rate, clamped to [1, 30] s, instead of a constant the client cannot
  trust.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..utils import concurrency as _conc

__all__ = ["RequestRejected", "DeadlineExceeded", "EngineClosed",
           "AdmissionController", "PRIORITIES", "priority_rank",
           "TenantQuotaTable", "DrainRateEstimator", "QuotaWatcher"]


# priority classes, best first; the rank (index) is what the engines
# order on — lower rank dequeues first
PRIORITIES = ("interactive", "standard", "batch")
_PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}


def priority_rank(priority: Optional[str]) -> int:
    """Rank of a priority-class name (0 = most urgent).  ``None``
    means ``standard``; unknown names raise ``ValueError`` (a typo'd
    header must 400, not silently become batch)."""
    if priority is None:
        return _PRIORITY_RANK["standard"]
    try:
        return _PRIORITY_RANK[priority]
    except KeyError:
        raise ValueError(
            f"unknown priority {priority!r}; expected one of "
            f"{list(PRIORITIES)}") from None


class RequestRejected(RuntimeError):
    """Explicit overload rejection; ``reason`` is one of ``queue_full``,
    ``too_large``, ``token_budget``, ``kv_blocks``, ``tenant_quota``,
    ``closed``.  ``kv_blocks`` means the paged KV block pool could not
    supply the request's blocks (possibly injected via the
    ``kv.block_alloc`` chaos site) — the engine shed it rather than
    corrupt a live batch; ``tenant_quota`` means the tenant's token
    bucket is empty.  ``retry_after`` (seconds, [1, 30]) is derived
    from the observed queue drain rate when the rejecting controller
    had one — the HTTP layer surfaces it as the ``Retry-After``
    header."""

    def __init__(self, msg: str, reason: str = "overload",
                 retry_after: Optional[int] = None):
        super().__init__(msg)
        self.reason = reason
        self.retry_after = retry_after


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before a result was produced.
    ``reason`` distinguishes WHERE it expired: ``deadline`` (queued or
    client-side wait) vs ``deadline_preempted`` (expired while the
    request sat preempted in host memory — the engine released the
    host-side state instead of resuming a stream nobody waits for)."""

    def __init__(self, msg: str = "", reason: str = "deadline"):
        super().__init__(msg)
        self.reason = reason


class EngineClosed(RequestRejected):
    def __init__(self, msg: str = "engine is closed"):
        super().__init__(msg, reason="closed")


class DrainRateEstimator:
    """Observed queue drain rate -> an honest ``Retry-After``.

    Every dequeue (request picked into the engine or shed) notes a
    timestamp; :meth:`retry_after_s` divides the current backlog by
    the drains-per-second observed over the rolling window and clamps
    to [1, 30] s.  A cold or stalled queue answers the ceiling — "come
    back in 30 s" is the only honest estimate when nothing has drained
    lately.  ``clock`` is injectable so tests freeze time.
    """

    FLOOR_S, CEIL_S = 1, 30

    def __init__(self, window_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.window_s = float(window_s)
        self._clock = clock
        self._events: list = []        # drain timestamps, ascending
        self._lock = _conc.Lock(name="serving.drain_rate")

    def note(self, n: int = 1):
        """``n`` requests left the queue now."""
        now = self._clock()
        with self._lock:
            self._events.extend([now] * int(n))
            cut = now - self.window_s
            while self._events and self._events[0] < cut:
                self._events.pop(0)

    def rate(self) -> float:
        """Drains per second over the window (0.0 when cold)."""
        now = self._clock()
        with self._lock:
            cut = now - self.window_s
            while self._events and self._events[0] < cut:
                self._events.pop(0)
            n = len(self._events)
            if n < 2:
                return 0.0
            span = max(now - self._events[0], 1e-6)
            return n / span

    def retry_after_s(self, depth: int) -> int:
        r = self.rate()
        if r <= 0.0:
            return self.CEIL_S if depth > 0 else self.FLOOR_S
        est = math.ceil(max(int(depth), 1) / r)
        return int(min(self.CEIL_S, max(self.FLOOR_S, est)))


class TenantQuotaTable:
    """Per-tenant token buckets for admission quotas.

    Config is ``{tenant: {"rate": tokens/s, "burst": max tokens}}``;
    the ``"*"`` entry is the default for tenants not named explicitly
    (no ``"*"`` -> unknown tenants are unlimited).  Buckets refill
    continuously at ``rate`` up to ``burst`` and are charged the
    request's true token cost (prompt + max_new) at admission —
    deterministic under an injected frozen ``clock``, which is what
    makes refill testable.  :meth:`reload` swaps the whole table
    atomically (existing buckets keep their level, clamped to the new
    burst), so a :class:`QuotaWatcher` can throttle a tenant without a
    restart.
    """

    def __init__(self, quotas: Optional[Dict[str, dict]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = _conc.Lock(name="serving.tenant_quota")
        self._cfg: Dict[str, Dict[str, float]] = {}
        self._buckets: Dict[str, list] = {}   # tenant -> [level, t]
        self.generation = 0
        if quotas:
            self.reload(quotas)

    @staticmethod
    def _validate(quotas: Dict[str, dict]) -> Dict[str, Dict[str, float]]:
        out = {}
        for tenant, q in dict(quotas).items():
            rate = float(q["rate"])
            burst = float(q.get("burst", rate))
            if rate < 0 or burst <= 0:
                raise ValueError(
                    f"quota for tenant {tenant!r}: rate must be >= 0 "
                    f"and burst > 0, got rate={rate} burst={burst}")
            out[str(tenant)] = {"rate": rate, "burst": burst}
        return out

    def reload(self, quotas: Dict[str, dict]) -> int:
        """Atomically replace the quota config (validated first — a
        malformed table changes nothing).  Returns the new generation
        counter."""
        cfg = self._validate(quotas)
        with self._lock:
            self._cfg = cfg
            for tenant in list(self._buckets):
                lim = cfg.get(tenant) or cfg.get("*")
                if lim is None:
                    del self._buckets[tenant]     # now unlimited
                else:
                    self._buckets[tenant][0] = min(
                        self._buckets[tenant][0], lim["burst"])
            self.generation += 1
            return self.generation

    def limit_for(self, tenant: str) -> Optional[Dict[str, float]]:
        with self._lock:
            return self._cfg.get(tenant) or self._cfg.get("*")

    def try_acquire(self, tenant: str, tokens: int) -> bool:
        """Charge ``tokens`` against the tenant's bucket; False means
        the quota is exhausted (the caller sheds typed).  Unlimited
        tenants always pass."""
        now = self._clock()
        tokens = max(int(tokens), 1)
        with self._lock:
            lim = self._cfg.get(tenant) or self._cfg.get("*")
            if lim is None:
                return True
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = [lim["burst"], now]
            level = min(lim["burst"],
                        bucket[0] + (now - bucket[1]) * lim["rate"])
            bucket[1] = now
            if level >= tokens:
                bucket[0] = level - tokens
                return True
            bucket[0] = level
            return False

    def level(self, tenant: str) -> Optional[float]:
        """Current bucket level (refilled-to-now); None = unlimited."""
        now = self._clock()
        with self._lock:
            lim = self._cfg.get(tenant) or self._cfg.get("*")
            if lim is None:
                return None
            bucket = self._buckets.get(tenant)
            if bucket is None:
                return lim["burst"]
            return min(lim["burst"],
                       bucket[0] + (now - bucket[1]) * lim["rate"])


class QuotaWatcher:
    """Hot-reload for tenant quota tables — the WeightWatcher pattern
    applied to config: poll a JSON file (``{tenant: {"rate": r,
    "burst": b}}``), verify, apply atomically between requests, so an
    operator throttles a tenant by editing a file, never by
    restarting the engine.  A malformed or unparsable table is
    rejected loudly and the previous config keeps serving (counted
    ``serving.quota.reload_rejected``); applied reloads count
    ``serving.quota.reloads`` and leave an ``admission.quota_reload``
    flight event."""

    def __init__(self, path: str, controller: "AdmissionController", *,
                 interval: float = 1.0):
        self.path = os.path.abspath(path)
        self.controller = controller
        self.interval = float(interval)
        self._mtime: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> bool:
        """Apply the file if it changed; True when a reload landed."""
        try:
            mtime = os.stat(self.path).st_mtime_ns
        except OSError:
            return False              # absent file: keep serving as-is
        if mtime == self._mtime:
            return False
        from ..profiler import flight as _flight
        from ..profiler import metrics as _metrics
        try:
            with open(self.path, "r") as f:
                quotas = json.load(f)
            if not isinstance(quotas, dict):
                raise ValueError("quota table must be a JSON object "
                                 "{tenant: {rate, burst}}")
            gen = self.controller.set_quotas(quotas)
        except Exception as e:   # noqa: BLE001 — old config keeps serving
            self._mtime = mtime   # don't re-warn every poll tick
            _metrics.counter(
                "serving.quota.reload_rejected",
                "quota-table reloads rejected (malformed file; the "
                "previous config kept serving)").inc()
            import warnings
            warnings.warn(f"quota watcher: {self.path} rejected "
                          f"({e!r}); previous quota table kept",
                          RuntimeWarning)
            return False
        self._mtime = mtime
        _metrics.counter(
            "serving.quota.reloads",
            "tenant quota tables hot-reloaded from disk").inc()
        if _flight.active:
            _flight.note("admission", "quota_reload",
                         path=self.path, tenants=len(quotas),
                         generation=gen)
        return True

    def _loop(self):
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception as e:  # noqa: BLE001 — watcher must survive
                import warnings
                warnings.warn(f"quota watcher poll failed ({e!r})",
                              RuntimeWarning)

    def start(self) -> "QuotaWatcher":
        self.poll_once()              # synchronous first read
        self._thread = _conc.spawn(self._loop, name="quota-watcher")
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)
            self._thread = None


class AdmissionController:
    """Bounded-queue gatekeeper with SLO counters.

    ``acquire``/``release`` bracket a request's time in the pending
    queue; the gauge tracks live depth so ``/metrics`` shows queue
    pressure directly.
    """

    def __init__(self, max_queue: int, max_rows: Optional[int] = None,
                 name: str = "serving",
                 max_tokens: Optional[int] = None,
                 quotas: Optional[TenantQuotaTable] = None):
        self.max_queue = int(max_queue)
        self.max_rows = max_rows
        # per-tenant token buckets (None = no quotas); swappable at
        # runtime via set_quotas (the QuotaWatcher hot-reload path)
        self.quotas = quotas
        # observed dequeue rate -> drain-derived Retry-After on sheds
        self.drain = DrainRateEstimator()
        # token budget (generation engines): the sum of every admitted
        # request's reserved tokens (prompt + max_new) may not exceed
        # this — cache slots and decode time are provisioned in tokens,
        # not requests, so admission must be too
        self.max_tokens = int(max_tokens) if max_tokens else None
        self._tokens = 0
        self._depth = 0
        self._lock = _conc.Lock(name=f"{name}.admission")
        from ..profiler import metrics as _metrics
        self._admitted = _metrics.counter(
            f"{name}.request.admitted", "requests accepted into the "
            "engine queue")
        self._rejected = _metrics.counter(
            f"{name}.request.rejected", "requests explicitly rejected "
            "at admission (all reasons)")
        self._shed = _metrics.counter(
            f"{name}.request.shed_deadline", "queued requests dropped "
            "because their deadline expired before execution")
        self._shed_kv = _metrics.counter(
            f"{name}.request.shed_kv_blocks", "admitted requests shed "
            "because the paged KV block pool could not supply blocks "
            "(incl. exhaustion injected via kv.block_alloc)")
        self._depth_gauge = _metrics.gauge(
            f"{name}.queue_depth", "requests currently waiting in the "
            "engine queue")
        self._tokens_gauge = _metrics.gauge(
            f"{name}.tokens_in_flight", "reserved tokens (prompt + max "
            "new) of admitted-but-unfinished generation requests")
        self._name = name
        self._closed = False

    # -- lifecycle ----------------------------------------------------
    def close(self):
        self._closed = True

    @property
    def depth(self) -> int:
        return self._depth

    # -- quotas / retry hints -----------------------------------------
    def set_quotas(self, quotas) -> int:
        """Install or replace the tenant quota table (dict config or a
        prebuilt :class:`TenantQuotaTable`) without disturbing
        admission — THE hot-reload entry point.  Returns the table's
        generation counter."""
        if quotas is None:
            self.quotas = None
            return 0
        if isinstance(quotas, TenantQuotaTable):
            self.quotas = quotas
            return quotas.generation
        table = self.quotas
        if table is None:
            table = TenantQuotaTable()
            gen = table.reload(quotas)
            self.quotas = table       # publish only after validation
            return gen
        return table.reload(quotas)

    def retry_after_s(self) -> int:
        """Drain-rate-derived retry hint for the CURRENT backlog,
        clamped to [1, 30] s."""
        return self.drain.retry_after_s(self._depth)

    def _tenant_counter(self, tenant: Optional[str], what: str):
        if not tenant:
            return None
        from ..profiler import metrics as _metrics
        return _metrics.counter(
            f"{self._name}.tenant.{tenant}.{what}",
            f"per-tenant SLO accounting: {what}")

    # -- admission ----------------------------------------------------
    def _reject(self, reason: str, msg: str,
                tenant: Optional[str] = None,
                priority: Optional[str] = None):
        from ..profiler import flight as _flight
        from ..profiler import metrics as _metrics
        retry_after = self.retry_after_s()
        with self._lock:   # exact counts even under concurrent clients
            self._rejected.inc()
            _metrics.counter(
                f"{self._name}.request.rejected.{reason}").inc()
            c = self._tenant_counter(tenant, "shed")
            if c is not None:
                c.inc()
        if _flight.active:
            _flight.note("admission", "reject", engine=self._name,
                         reason=reason, tenant=tenant,
                         priority=priority)
            if reason == "tenant_quota":
                # the event the slo gate counts exactly (request_id is
                # stamped from the ambient rtrace context)
                _flight.note("admission", "tenant_quota",
                             engine=self._name, tenant=tenant,
                             priority=priority,
                             retry_after=retry_after)
        if reason == "closed":
            exc = EngineClosed(msg)
            exc.retry_after = retry_after
            raise exc
        raise RequestRejected(msg, reason=reason,
                              retry_after=retry_after)

    def reject(self, reason: str, msg: str,
               tenant: Optional[str] = None,
               priority: Optional[str] = None):
        """Shed a request through the standard typed-rejection path
        (counted ``request.rejected`` + ``request.rejected.<reason>``,
        flight-noted, Retry-After attached) for policy reasons the
        controller cannot see itself — e.g. a disaggregated
        prefill-role replica refusing a full-decode request
        (``reason="wrong_role"``).  Always raises."""
        self._reject(reason, msg, tenant=tenant, priority=priority)

    def acquire(self, rows: int = 1, tokens: int = 0,
                tenant: Optional[str] = None,
                priority: Optional[str] = None,
                quota_tokens: Optional[int] = None):
        """Admit one request of ``rows`` samples (reserving ``tokens``
        against the token budget, when one is configured) or raise
        :class:`RequestRejected`.  ``tenant``/``priority`` feed the
        per-tenant accounting; ``quota_tokens`` is the cost charged to
        the tenant's token bucket (defaults to ``tokens``)."""
        if self._closed:
            self._reject("closed", "engine is closed", tenant=tenant,
                         priority=priority)
        if self.max_rows is not None and rows > self.max_rows:
            self._reject(
                "too_large",
                f"request carries {rows} rows but max_batch_size is "
                f"{self.max_rows}; split the request (a batch the "
                "engine could never place would wait forever)",
                tenant=tenant, priority=priority)
        if self.max_tokens is not None and tokens > self.max_tokens:
            self._reject(
                "too_large",
                f"request reserves {tokens} tokens but the engine's "
                f"whole token budget is {self.max_tokens}; shorten the "
                "prompt or max_new_tokens", tenant=tenant,
                priority=priority)
        quotas = self.quotas
        if tenant and quotas is not None and not quotas.try_acquire(
                tenant, quota_tokens if quota_tokens is not None
                else tokens):
            self._reject(
                "tenant_quota",
                f"tenant {tenant!r} token bucket exhausted; the quota "
                "refills continuously — honor Retry-After",
                tenant=tenant, priority=priority)
        reason = None
        with self._lock:
            if self._depth >= self.max_queue:
                reason, depth = "queue_full", self._depth
            elif self.max_tokens is not None and \
                    self._tokens + tokens > self.max_tokens:
                reason, held = "token_budget", self._tokens
            else:
                self._depth += 1
                self._depth_gauge.set(self._depth)
                self._tokens += tokens
                self._tokens_gauge.set(self._tokens)
                self._admitted.inc()
                c = self._tenant_counter(tenant, "admitted")
                if c is not None:
                    c.inc()
                from ..profiler import flight as _flight
                if _flight.active:
                    _flight.note("admission", "admit",
                                 engine=self._name, depth=self._depth,
                                 tenant=tenant, priority=priority)
                return
        if reason == "queue_full":
            self._reject(
                "queue_full",
                f"engine queue is full ({depth}/{self.max_queue} "
                "waiting); overload is shed explicitly — retry with "
                "backoff or scale workers (EngineConfig.max_queue "
                "bounds this)", tenant=tenant, priority=priority)
        self._reject(
            "token_budget",
            f"token budget exhausted ({held}+{tokens} over "
            f"{self.max_tokens} reserved tokens in flight); retry when "
            "running generations finish (max_tokens_in_flight bounds "
            "this)", tenant=tenant, priority=priority)

    def release(self):
        """The request left the queue (picked into a batch or shed)."""
        with self._lock:
            self._depth = max(0, self._depth - 1)
            self._depth_gauge.set(self._depth)
        self.drain.note()

    def release_tokens(self, tokens: int):
        """A generation request retired (finished / shed / failed):
        return its reserved tokens to the budget."""
        if not tokens:
            return
        with self._lock:
            self._tokens = max(0, self._tokens - int(tokens))
            self._tokens_gauge.set(self._tokens)

    def shed_deadline(self, preempted: bool = False):
        """A deadline expired before a result: while queued (default)
        or — ``preempted=True`` — while the request sat swapped out in
        host memory (typed ``deadline_preempted``; the engine released
        the host-side state instead of resuming a dead stream)."""
        self._shed.inc()
        from ..profiler import flight as _flight
        if preempted:
            from ..profiler import metrics as _metrics
            _metrics.counter(
                f"{self._name}.request.shed_deadline_preempted",
                "preempted-to-host requests dropped because their "
                "deadline expired while swapped out").inc()
            if _flight.active:
                _flight.note("admission", "deadline_preempted",
                             engine=self._name)
            return
        if _flight.active:
            _flight.note("admission", "shed_deadline",
                         engine=self._name)

    def shed_kv_blocks(self):
        """A paged engine shed an admitted request on pool exhaustion
        (typed ``RequestRejected(reason="kv_blocks")`` to the client —
        the gate asserts this count exactly)."""
        self._shed_kv.inc()
        from ..profiler import flight as _flight
        if _flight.active:
            _flight.note("admission", "shed_kv_blocks",
                         engine=self._name)


def deadline_from_ms(deadline_ms: Optional[float]) -> Optional[float]:
    """Monotonic absolute deadline from a relative millisecond budget."""
    if deadline_ms is None:
        return None
    return time.monotonic() + float(deadline_ms) / 1e3

"""Admission control for the serving engine: shed, don't OOM.

An overloaded accelerator endpoint has exactly three honest choices:
queue (bounded!), reject explicitly, or fall over.  This module owns
the first two.  Every request passes :meth:`AdmissionController.admit`
before it may enter the engine queue:

- queue depth beyond ``max_queue``  -> :class:`RequestRejected`
  (``queue_full``) — the client sees backpressure immediately instead
  of a timeout after unbounded buffering;
- request rows beyond ``max_batch_size`` -> :class:`RequestRejected`
  (``too_large``) — a request the batcher could never place;
- engine closed -> :class:`RequestRejected` (``closed``).

Per-request deadlines produce :class:`DeadlineExceeded` when a request
expires while still queued (the batcher sheds it without running) or
when the client-side wait runs out.  All outcomes are SLO-accounted in
the metrics registry: ``serving.request.admitted``,
``serving.request.rejected[.reason]``, ``serving.request.shed_deadline``,
``serving.queue_depth``.
"""
from __future__ import annotations

import time
from typing import Optional

from ..utils import concurrency as _conc

__all__ = ["RequestRejected", "DeadlineExceeded", "EngineClosed",
           "AdmissionController"]


class RequestRejected(RuntimeError):
    """Explicit overload rejection; ``reason`` is one of ``queue_full``,
    ``too_large``, ``token_budget``, ``kv_blocks``, ``closed``.
    ``kv_blocks`` means the paged KV block pool could not supply the
    request's blocks (possibly injected via the ``kv.block_alloc``
    chaos site) — the engine shed it rather than corrupt a live
    batch."""

    def __init__(self, msg: str, reason: str = "overload"):
        super().__init__(msg)
        self.reason = reason


class DeadlineExceeded(TimeoutError):
    """The request's deadline passed before a result was produced."""


class EngineClosed(RequestRejected):
    def __init__(self, msg: str = "engine is closed"):
        super().__init__(msg, reason="closed")


class AdmissionController:
    """Bounded-queue gatekeeper with SLO counters.

    ``acquire``/``release`` bracket a request's time in the pending
    queue; the gauge tracks live depth so ``/metrics`` shows queue
    pressure directly.
    """

    def __init__(self, max_queue: int, max_rows: Optional[int] = None,
                 name: str = "serving",
                 max_tokens: Optional[int] = None):
        self.max_queue = int(max_queue)
        self.max_rows = max_rows
        # token budget (generation engines): the sum of every admitted
        # request's reserved tokens (prompt + max_new) may not exceed
        # this — cache slots and decode time are provisioned in tokens,
        # not requests, so admission must be too
        self.max_tokens = int(max_tokens) if max_tokens else None
        self._tokens = 0
        self._depth = 0
        self._lock = _conc.Lock(name=f"{name}.admission")
        from ..profiler import metrics as _metrics
        self._admitted = _metrics.counter(
            f"{name}.request.admitted", "requests accepted into the "
            "engine queue")
        self._rejected = _metrics.counter(
            f"{name}.request.rejected", "requests explicitly rejected "
            "at admission (all reasons)")
        self._shed = _metrics.counter(
            f"{name}.request.shed_deadline", "queued requests dropped "
            "because their deadline expired before execution")
        self._shed_kv = _metrics.counter(
            f"{name}.request.shed_kv_blocks", "admitted requests shed "
            "because the paged KV block pool could not supply blocks "
            "(incl. exhaustion injected via kv.block_alloc)")
        self._depth_gauge = _metrics.gauge(
            f"{name}.queue_depth", "requests currently waiting in the "
            "engine queue")
        self._tokens_gauge = _metrics.gauge(
            f"{name}.tokens_in_flight", "reserved tokens (prompt + max "
            "new) of admitted-but-unfinished generation requests")
        self._name = name
        self._closed = False

    # -- lifecycle ----------------------------------------------------
    def close(self):
        self._closed = True

    @property
    def depth(self) -> int:
        return self._depth

    # -- admission ----------------------------------------------------
    def _reject(self, reason: str, msg: str):
        from ..profiler import flight as _flight
        from ..profiler import metrics as _metrics
        with self._lock:   # exact counts even under concurrent clients
            self._rejected.inc()
            _metrics.counter(
                f"{self._name}.request.rejected.{reason}").inc()
        if _flight.active:
            _flight.note("admission", "reject", engine=self._name,
                         reason=reason)
        if reason == "closed":
            raise EngineClosed(msg)
        raise RequestRejected(msg, reason=reason)

    def acquire(self, rows: int = 1, tokens: int = 0):
        """Admit one request of ``rows`` samples (reserving ``tokens``
        against the token budget, when one is configured) or raise
        :class:`RequestRejected`."""
        if self._closed:
            self._reject("closed", "engine is closed")
        if self.max_rows is not None and rows > self.max_rows:
            self._reject(
                "too_large",
                f"request carries {rows} rows but max_batch_size is "
                f"{self.max_rows}; split the request (a batch the "
                "engine could never place would wait forever)")
        if self.max_tokens is not None and tokens > self.max_tokens:
            self._reject(
                "too_large",
                f"request reserves {tokens} tokens but the engine's "
                f"whole token budget is {self.max_tokens}; shorten the "
                "prompt or max_new_tokens")
        reason = None
        with self._lock:
            if self._depth >= self.max_queue:
                reason, depth = "queue_full", self._depth
            elif self.max_tokens is not None and \
                    self._tokens + tokens > self.max_tokens:
                reason, held = "token_budget", self._tokens
            else:
                self._depth += 1
                self._depth_gauge.set(self._depth)
                self._tokens += tokens
                self._tokens_gauge.set(self._tokens)
                self._admitted.inc()
                from ..profiler import flight as _flight
                if _flight.active:
                    _flight.note("admission", "admit",
                                 engine=self._name, depth=self._depth)
                return
        if reason == "queue_full":
            self._reject(
                "queue_full",
                f"engine queue is full ({depth}/{self.max_queue} "
                "waiting); overload is shed explicitly — retry with "
                "backoff or scale workers (EngineConfig.max_queue "
                "bounds this)")
        self._reject(
            "token_budget",
            f"token budget exhausted ({held}+{tokens} over "
            f"{self.max_tokens} reserved tokens in flight); retry when "
            "running generations finish (max_tokens_in_flight bounds "
            "this)")

    def release(self):
        """The request left the queue (picked into a batch or shed)."""
        with self._lock:
            self._depth = max(0, self._depth - 1)
            self._depth_gauge.set(self._depth)

    def release_tokens(self, tokens: int):
        """A generation request retired (finished / shed / failed):
        return its reserved tokens to the budget."""
        if not tokens:
            return
        with self._lock:
            self._tokens = max(0, self._tokens - int(tokens))
            self._tokens_gauge.set(self._tokens)

    def shed_deadline(self):
        self._shed.inc()
        from ..profiler import flight as _flight
        if _flight.active:
            _flight.note("admission", "shed_deadline",
                         engine=self._name)

    def shed_kv_blocks(self):
        """A paged engine shed an admitted request on pool exhaustion
        (typed ``RequestRejected(reason="kv_blocks")`` to the client —
        the gate asserts this count exactly)."""
        self._shed_kv.inc()
        from ..profiler import flight as _flight
        if _flight.active:
            _flight.note("admission", "shed_kv_blocks",
                         engine=self._name)


def deadline_from_ms(deadline_ms: Optional[float]) -> Optional[float]:
    """Monotonic absolute deadline from a relative millisecond budget."""
    if deadline_ms is None:
        return None
    return time.monotonic() + float(deadline_ms) / 1e3

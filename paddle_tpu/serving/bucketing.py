"""Shape bucketing + compiled-executable cache for serving.

Every novel input shape handed to an exported XLA program is a full
retrace + compile (seconds); a serving fleet that forwards raw client
shapes compiles without bound.  Bucketing rounds the batch dim (and,
opt-in, any dynamic non-batch dim) up to the next power-of-two bucket,
so the executable population is bounded by the bucket count no matter
what shapes clients send: ``ceil(log2(max_batch_size)) + 1`` batch
buckets instead of one program per observed batch size.

The :class:`ExecutableCache` maps ``(bucket signature, precision)`` to
an ahead-of-time compiled executable (``jax.jit(...).lower().compile()``)
shared by every predictor worker; misses are compiles and are counted
(``serving.compile``), which is what the serving gate bounds.

Reference points: Orca/Clipper-style serving batchers bucket padded
batches the same way; the reference Paddle bounds executables per
predictor via its ZeroCopy shape contract.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils import concurrency as _conc

__all__ = ["next_bucket", "bucket_shape", "pad_batch", "seq_buckets",
           "BucketPolicy", "ExecutableCache"]


def next_bucket(n: int, min_bucket: int = 1, cap: Optional[int] = None
                ) -> int:
    """Smallest power-of-two >= ``n`` (and >= ``min_bucket``), clamped
    to ``cap`` when given.  ``n`` itself is returned when it exceeds the
    cap — the caller decided to admit it, so it gets its own bucket."""
    if n < 0:
        raise ValueError(f"bucket size for negative dim {n}")
    b = max(int(min_bucket), 1)
    while b < n:
        b <<= 1
    if cap is not None and b > cap:
        return n if n > cap else cap
    return b


class BucketPolicy:
    """Which dims of each input get padded, and to what bucket.

    ``dynamic_dims`` comes from the artifact's declared input avals
    (``-1`` entries).  Dim 0 of every input is the batch dim and is
    always bucketed (the engine concatenates requests along it); other
    dynamic dims are bucketed only with ``pad_dynamic_dims=True``
    because zero-padding a content dim (e.g. sequence length) changes
    the math for models without masking — the engine documents that
    contract rather than silently corrupting outputs.
    """

    def __init__(self, input_avals: Sequence[Tuple[Sequence[int], str]],
                 max_batch_size: int = 8, min_batch_bucket: int = 1,
                 pad_dynamic_dims: bool = False):
        self.max_batch_size = int(max_batch_size)
        self.min_batch_bucket = int(min_batch_bucket)
        self.pad_dynamic_dims = bool(pad_dynamic_dims)
        # per input: indices of non-batch dims declared dynamic
        self.dynamic_dims: List[Tuple[int, ...]] = []
        for shape, _dt in input_avals or ():
            self.dynamic_dims.append(tuple(
                i for i, d in enumerate(shape)
                if i > 0 and (d is None or int(d) < 0)))

    def batch_bucket(self, rows: int) -> int:
        return next_bucket(rows, self.min_batch_bucket,
                           cap=self.max_batch_size)

    def bucket_shape(self, idx: int, shape: Sequence[int],
                     rows_bucket: int) -> Tuple[int, ...]:
        """Padded shape for input ``idx``: batch dim -> ``rows_bucket``,
        dynamic dims -> pow2 buckets when enabled, rest untouched."""
        out = list(shape)
        out[0] = rows_bucket
        if self.pad_dynamic_dims and idx < len(self.dynamic_dims):
            for d in self.dynamic_dims[idx]:
                if d < len(out):
                    out[d] = next_bucket(out[d])
        return tuple(out)

    def max_buckets(self) -> int:
        """Upper bound on batch buckets (compile-count bound when
        dynamic-dim padding is off and non-batch shapes are fixed)."""
        n, b = 1, max(self.min_batch_bucket, 1)
        while b < self.max_batch_size:
            b <<= 1
            n += 1
        return n


def seq_buckets(max_length: int, min_bucket: int = 8) -> List[int]:
    """All pow2 sequence-length buckets in ``[min_bucket, max_length]``
    (the last clamps to ``max_length``).  This is the compile-count
    bound for generation prefill: one prefill executable per entry (+1
    decode executable per batch capacity) no matter how many prompts
    of how many lengths arrive — what ``tools/decode_gate.py`` and the
    bench assert against."""
    out, b = [], max(1, int(min_bucket))
    cap = max(1, int(max_length))
    while b < cap:
        out.append(b)
        b <<= 1
    out.append(cap)
    return out


def bucket_shape(shape: Sequence[int], max_batch_size: int = 8
                 ) -> Tuple[int, ...]:
    """Convenience: bucket dim 0 of ``shape`` (pow2, capped)."""
    out = list(shape)
    out[0] = next_bucket(out[0], cap=max_batch_size)
    return tuple(out)


def pad_batch(arr, target_shape: Sequence[int]):
    """Zero-pad ``arr`` up to ``target_shape`` (no dim may shrink).
    Returns ``arr`` unchanged when the shape already matches."""
    import numpy as np
    shape = tuple(arr.shape)
    target = tuple(int(t) for t in target_shape)
    if shape == target:
        return arr
    if len(shape) != len(target) or any(t < s for s, t in zip(shape,
                                                              target)):
        raise ValueError(f"cannot pad {shape} to {target}")
    out = np.zeros(target, dtype=arr.dtype)
    out[tuple(slice(0, s) for s in shape)] = np.asarray(arr)
    return out


class ExecutableCache:
    """``(bucket signature, precision) -> compiled executable``.

    ``get_or_compile`` is hit/miss accounted in the metrics registry
    (``serving.executable_cache.hit`` / ``serving.compile``); a miss
    runs ``compile_fn`` exactly once per key even under concurrent
    workers (per-key in-flight latch), so total compiles stay bounded
    by the number of distinct bucket keys.
    """

    def __init__(self, name: str = "serving"):
        self._name = name
        self._lock = _conc.Lock(name=f"{name}.executable_cache")
        self._entries: Dict[Tuple, object] = {}
        self._inflight: Dict[Tuple, threading.Event] = {}
        from ..profiler import metrics as _metrics
        self._hits = _metrics.counter(
            f"{name}.executable_cache.hit",
            "bucketed executions served by an already-compiled "
            "executable")
        self._compiles = _metrics.counter(
            f"{name}.compile",
            "executable-cache misses == XLA compiles; bounded by the "
            "bucket count")

    def __len__(self):
        return len(self._entries)

    def keys(self):
        with self._lock:
            return list(self._entries)

    def get_or_compile(self, key: Tuple, compile_fn: Callable[[], object]):
        while True:
            with self._lock:
                if key in self._entries:
                    self._hits.inc()
                    return self._entries[key]
                latch = self._inflight.get(key)
                if latch is None:
                    self._inflight[key] = latch = threading.Event()
                    owner = True
                else:
                    owner = False
            if not owner:
                latch.wait()
                continue  # re-read: owner published (or failed)
            try:
                from ..profiler import memscope as _memscope
                if _memscope.active:
                    import time as _time
                    _c0 = _time.perf_counter()
                    exe = compile_fn()
                    _memscope.compile_record(
                        self._name, key, _time.perf_counter() - _c0,
                        provenance="jit")
                else:
                    exe = compile_fn()
                with self._lock:
                    self._entries[key] = exe
                    # under the lock: the registry's inc is lock-free
                    # and the serving gate asserts exact compile counts
                    self._compiles.inc()
                return exe
            finally:
                with self._lock:
                    self._inflight.pop(key, None)
                latch.set()

"""paddle.text namespace (reference python/paddle/text/__init__.py)."""
from .viterbi_decode import ViterbiDecoder, viterbi_decode  # noqa: F401
from .tokenizer import FasterTokenizer, to_string_tensor  # noqa: F401
from .datasets import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
                       UCIHousing, WMT14, WMT16)

__all__ = ["FasterTokenizer", "to_string_tensor",
           "ViterbiDecoder", "viterbi_decode", "Conll05st", "Imdb",
           "Imikolov", "Movielens", "UCIHousing", "WMT14", "WMT16"]

"""FasterTokenizer — in-graph-style BERT tokenization.

Reference parity: ``paddle/fluid/operators/string/faster_tokenizer_op.h``
(the ``faster_tokenizer`` op: BasicTokenizer + WordPieceTokenizer fused
into one C++ kernel so serving graphs tokenize without Python).

TPU translation: tokenization is host-side string work in the reference
too (a CPU-only kernel); here it is a host layer producing device int
tensors (input_ids, token_type_ids) ready for an embedding lookup.  The
algorithmics match the reference kernel: NFD-free basic cleaning,
CJK-char isolation, punctuation splitting, lowercase+accent-strip, then
greedy longest-match-first wordpiece with the ``##`` continuation
prefix and UNK fallback.
"""
from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn import Layer

__all__ = ["FasterTokenizer", "to_string_tensor"]


def to_string_tensor(strings, name=None):
    """Compat shim: the reference wraps strings into a string Variable
    (framework::Strings); here plain python lists flow to the layer."""
    return list(strings)


def _is_whitespace(ch):
    return ch in " \t\n\r" or unicodedata.category(ch) == "Zs"


def _is_control(ch):
    if ch in ("\t", "\n", "\r"):
        return False
    return unicodedata.category(ch).startswith("C")


def _is_punctuation(ch):
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96 or
            123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(cp):
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF or
            0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2B73F or
            0x2B740 <= cp <= 0x2B81F or 0x2B820 <= cp <= 0x2CEAF or
            0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


class FasterTokenizer(Layer):
    """BERT tokenizer layer (reference faster_tokenizer_op.h:269
    ``TokenizerOp``).  vocab: dict token->id or path to a vocab.txt."""

    def __init__(self, vocab: Union[Dict[str, int], str],
                 do_lower_case: bool = True,
                 is_split_into_words: bool = False,
                 unk_token: str = "[UNK]", cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]",
                 max_input_chars_per_word: int = 100):
        super().__init__()
        if isinstance(vocab, str):
            with open(vocab, encoding="utf-8") as f:
                vocab = {line.rstrip("\n"): i
                         for i, line in enumerate(f) if line.strip()}
        self.vocab = dict(vocab)
        self.do_lower_case = do_lower_case
        self.is_split_into_words = is_split_into_words
        self.unk_token, self.cls_token = unk_token, cls_token
        self.sep_token, self.pad_token = sep_token, pad_token
        self.max_input_chars_per_word = max_input_chars_per_word

    # -- basic tokenizer ---------------------------------------------------
    def _clean(self, text: str) -> str:
        out = []
        for ch in text:
            cp = ord(ch)
            if cp == 0 or cp == 0xFFFD or _is_control(ch):
                continue
            out.append(" " if _is_whitespace(ch) else ch)
        return "".join(out)

    def _basic(self, text: str) -> List[str]:
        text = self._clean(text)
        # isolate CJK chars so each becomes its own token
        text = "".join(f" {c} " if _is_cjk(ord(c)) else c for c in text)
        tokens = []
        for tok in text.split():
            if self.do_lower_case:
                tok = tok.lower()
                tok = "".join(c for c in unicodedata.normalize("NFD", tok)
                              if unicodedata.category(c) != "Mn")
            # split punctuation off
            cur = []
            for ch in tok:
                if _is_punctuation(ch):
                    if cur:
                        tokens.append("".join(cur))
                        cur = []
                    tokens.append(ch)
                else:
                    cur.append(ch)
            if cur:
                tokens.append("".join(cur))
        return tokens

    # -- wordpiece ---------------------------------------------------------
    def _wordpiece(self, token: str) -> List[str]:
        if len(token) > self.max_input_chars_per_word:
            return [self.unk_token]
        out, start = [], 0
        while start < len(token):
            end = len(token)
            cur = None
            while start < end:
                piece = token[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    cur = piece
                    break
                end -= 1
            if cur is None:
                return [self.unk_token]
            out.append(cur)
            start = end
        return out

    def tokenize(self, text: str) -> List[str]:
        if self.is_split_into_words:
            words = text.split() if isinstance(text, str) else list(text)
        else:
            words = self._basic(text)
        out = []
        for w in words:
            out.extend(self._wordpiece(w))
        return out

    # -- decode (the streaming-serving contract) ---------------------------
    def convert_ids_to_tokens(self, ids) -> List[str]:
        """Inverse vocab lookup (unknown ids -> the unk token)."""
        if getattr(self, "_inv_vocab", None) is None or \
                len(self._inv_vocab) != len(self.vocab):
            self._inv_vocab = {i: t for t, i in self.vocab.items()}
        inv = self._inv_vocab
        return [inv.get(int(i), self.unk_token) for i in np.asarray(
            getattr(ids, "_data", ids)).reshape(-1)]

    def decode(self, ids, skip_special_tokens: bool = True) -> str:
        """Token ids -> text: wordpieces merge at their ``##``
        continuation prefix, words join with single spaces.  This is
        the contract the token-streaming serving path leans on:
        ``decode(encode(text))`` round-trips any text that is already
        clean, lower-case, whitespace-delimited, in-vocab wordpiece
        material (tests/test_tokenizer.py pins it) — tokenization is
        lossy beyond that (case folding, accent stripping, whitespace
        collapse) by design.  ``skip_special_tokens`` drops
        [CLS]/[SEP]/[PAD]/[UNK]-style framing."""
        specials = {self.cls_token, self.sep_token, self.pad_token}
        if skip_special_tokens:
            specials.add(self.unk_token)
        pieces = []
        for tok in self.convert_ids_to_tokens(ids):
            if skip_special_tokens and tok in specials:
                continue
            if tok.startswith("##") and pieces:
                pieces[-1] += tok[2:]
            else:
                pieces.append(tok)
        return " ".join(pieces)

    def forward(self, text, text_pair=None, max_seq_len=0,
                pad_to_max_seq_len=False):
        """Returns (input_ids [B, L], token_type_ids [B, L]) int64
        tensors, [CLS]/[SEP]-framed like the reference op."""
        if isinstance(text, str):
            text = [text]
        if isinstance(text_pair, str):
            text_pair = [text_pair]
        v = self.vocab
        unk = v.get(self.unk_token, 0)
        cls_id, sep_id = v[self.cls_token], v[self.sep_token]
        pad_id = v.get(self.pad_token, 0)
        seqs: List[Tuple[List[int], List[int]]] = []
        for i, t in enumerate(text):
            ids_a = [v.get(tok, unk) for tok in self.tokenize(t)]
            ids_b = ([v.get(tok, unk) for tok in
                      self.tokenize(text_pair[i])]
                     if text_pair is not None else None)
            if max_seq_len > 0:
                budget = max(max_seq_len - 2
                             - (1 if ids_b is not None else 0), 0)
                if ids_b is not None:
                    # truncate the longer first (reference behavior)
                    while (len(ids_a) + len(ids_b) > budget
                           and (ids_a or ids_b)):
                        (ids_a if len(ids_a) >= len(ids_b)
                         else ids_b).pop()
                else:
                    ids_a = ids_a[:budget]
            ids = [cls_id] + ids_a + [sep_id]
            types = [0] * len(ids)
            if ids_b is not None:
                ids += ids_b + [sep_id]
                types += [1] * (len(ids_b) + 1)
            seqs.append((ids, types))
        width = (max_seq_len if (pad_to_max_seq_len and max_seq_len > 0)
                 else max(len(s[0]) for s in seqs))
        input_ids = np.full((len(seqs), width), pad_id, np.int64)
        type_ids = np.zeros((len(seqs), width), np.int64)
        for i, (ids, types) in enumerate(seqs):
            n = min(len(ids), width)    # framing tokens can exceed a tiny
            input_ids[i, :n] = ids[:n]  # max_seq_len; keep the row shape
            type_ids[i, :n] = types[:n]
        return (Tensor(jnp.asarray(input_ids)),
                Tensor(jnp.asarray(type_ids)))

"""Viterbi decoding (reference python/paddle/text/viterbi_decode.py:23,87).

TPU-first: the reference implements this as a C++/CUDA ``viterbi_decode``
op; here the whole dynamic program is a ``lax.scan`` over the time axis —
one fused XLA loop (forward max-product + backpointer record) and a second
reverse scan for the backtrace, all batched over [B].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..core.dispatch import dispatch
from ..core.tensor import Tensor, to_tensor
from ..nn.layer_base import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def _viterbi_arrays(potentials, transitions, lengths, include_bos_eos_tag):
    B, T, N = potentials.shape
    lengths = lengths.astype(jnp.int32)

    alpha0 = potentials[:, 0, :]
    if include_bos_eos_tag:
        # last row of transitions = start tag -> tag scores
        alpha0 = alpha0 + transitions[N - 1][None, :]

    def fwd(carry, xs):
        alpha, t = carry
        emit = xs  # [B, N]
        # score[b, prev, cur] = alpha[b, prev] + transitions[prev, cur]
        scores = alpha[:, :, None] + transitions[None, :, :]
        best_prev = jnp.argmax(scores, axis=1)            # [B, N]
        alpha_new = jnp.max(scores, axis=1) + emit        # [B, N]
        active = (t < lengths)[:, None]                   # step t is real
        alpha = jnp.where(active, alpha_new, alpha)
        best_prev = jnp.where(active, best_prev,
                              jnp.arange(N)[None, :])     # identity carry
        return (alpha, t + 1), best_prev

    (alpha, _), backptrs = lax.scan(
        fwd, (alpha0, jnp.int32(1)),
        jnp.moveaxis(potentials[:, 1:, :], 1, 0))          # [T-1, B, N]

    if include_bos_eos_tag:
        # second-to-last column = tag -> stop transition
        alpha = alpha + transitions[:, N - 2][None, :]

    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)  # [B]

    def bwd(tag, xs):
        bp, t = xs                                         # bp: [B, N]
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        # only follow the pointer while inside the sequence
        tag = jnp.where(t < lengths, prev.astype(jnp.int32), tag)
        return tag, tag

    ts = jnp.arange(1, T, dtype=jnp.int32)
    _, rev_tags = lax.scan(bwd, last_tag, (backptrs[::-1], ts[::-1]))
    # rev_tags[k] = tag at time T-2-k ; full path = tags..., last position
    # of each row is the tag at its (length-1) step, carried to the right.
    path = jnp.concatenate([rev_tags[::-1],
                            last_tag[None, :]], axis=0)    # [T, B]
    path = jnp.moveaxis(path, 0, 1)                        # [B, T]
    # zero out the positions beyond each sequence's length
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    path = jnp.where(mask, path, 0)
    # x64 is off framework-wide: int64 canonicalizes to int32
    return scores, path.astype(jnp.int32)


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Highest-scoring tag sequence (reference ``viterbi_decode.py:23``).

    Args mirror the reference: ``potentials`` [B, T, N] unary emissions,
    ``transition_params`` [N, N], ``lengths`` [B].  Returns
    ``(scores [B], paths [B, T])``.
    """
    potentials = to_tensor(potentials)
    transition_params = to_tensor(transition_params)
    lengths = to_tensor(lengths)

    def _fn(p, t, l):
        return _viterbi_arrays(p, t, l, include_bos_eos_tag)

    out = dispatch("viterbi_decode", _fn,
                   (potentials, transition_params, lengths), {})
    return out


class ViterbiDecoder(Layer):
    """Layer wrapper (reference ``viterbi_decode.py:87``)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = to_tensor(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag
        self.name = name

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag, self.name)

"""paddle.text.datasets (reference python/paddle/text/datasets/).

Zero-egress image: the reference datasets stream from public mirrors; here
each dataset yields a deterministic synthetic corpus with the exact field
structure, dtypes and vocabulary sizes the reference documents, so NLP
pipelines (embedding lookup, padding, bucketing, seq2seq feed) exercise
end-to-end.  ``UCIHousing`` additionally parses a locally provided
``data_file`` (whitespace float table); the archive-format corpora raise
if one is passed rather than silently ignoring it.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16"]


class _SyntheticCorpus(Dataset):
    """Deterministic pool re-indexed to the advertised length."""

    _n_train = 2000
    _n_test = 400
    _pool = 512

    _MODE_SEED = {"train": 0, "test": 1, "dev": 2}

    def __init__(self, mode="train", data_file=None):
        if mode not in self._MODE_SEED:
            raise ValueError(f"mode must be train/test/dev, got {mode}")
        if data_file is not None:
            raise NotImplementedError(
                f"{type(self).__name__} cannot parse the reference archive "
                "format offline; omit data_file to use the synthetic corpus")
        self.mode = mode
        self.data_file = data_file
        self._len = self._n_train if mode == "train" else self._n_test
        self._rng = np.random.RandomState(self._MODE_SEED[mode])
        self._samples = [self._make(self._rng) for _ in range(self._pool)]

    def _make(self, rng):
        raise NotImplementedError

    def __getitem__(self, idx):
        # copy so in-place mutation by consumers can't corrupt the pool
        sample = self._samples[idx % self._pool]
        copy = lambda f: np.copy(f) if isinstance(f, np.ndarray) else f
        return tuple(copy(f) for f in sample) \
            if isinstance(sample, tuple) else copy(sample)

    def __len__(self):
        return self._len


class UCIHousing(_SyntheticCorpus):
    """13 float features -> house price (reference
    ``text/datasets/uci_housing.py``)."""

    _n_train = 404
    _n_test = 102

    def __init__(self, mode="train", data_file=None):
        if data_file is not None:
            if mode not in self._MODE_SEED:
                raise ValueError(f"mode must be train/test/dev, got {mode}")
            # the UCI format is a plain whitespace float table: parse it
            table = np.loadtxt(os.path.expanduser(data_file),
                               dtype=np.float32)
            if table.ndim != 2 or table.shape[1] != 14:
                raise ValueError(
                    f"expected a [N, 14] float table in {data_file}, got "
                    f"shape {table.shape}")
            split = int(len(table) * 0.8)
            rows = table[:split] if mode == "train" else table[split:]
            self.mode = mode
            self.data_file = data_file
            self._samples = [(r[:13], r[13:14]) for r in rows]
            self._pool = len(self._samples)
            self._len = len(self._samples)
            return
        super().__init__(mode=mode)

    def _make(self, rng):
        x = rng.rand(13).astype("float32")
        y = np.asarray([float(x.sum() / 13.0 * 50.0)], "float32")
        return x, y


class Imdb(_SyntheticCorpus):
    """Movie-review word-id sequence -> binary sentiment (reference
    ``text/datasets/imdb.py``; vocab ~5147)."""

    word_idx_size = 5147

    def _make(self, rng):
        n = rng.randint(16, 128)
        doc = rng.randint(0, self.word_idx_size, size=(n,)).astype("int64")
        label = np.asarray(int(doc[0] % 2), "int64")
        return doc, label

    @property
    def word_idx(self):
        # spans the full vocab so nn.Embedding(len(ds.word_idx), D) covers
        # every id a sample can contain; built once
        if not hasattr(self, "_word_idx"):
            self._word_idx = {f"w{i}": i for i in range(self.word_idx_size)}
        return self._word_idx


class Imikolov(_SyntheticCorpus):
    """PTB-style n-gram tuples (reference ``text/datasets/imikolov.py``)."""

    def __init__(self, mode="train", data_type="NGRAM", window_size=5,
                 min_word_freq=50, data_file=None):
        self.data_type = data_type
        self.window_size = window_size
        self.min_word_freq = min_word_freq
        self.word_idx_size = 2074
        super().__init__(mode=mode, data_file=data_file)

    def _make(self, rng):
        if self.data_type.upper() == "NGRAM":
            return tuple(rng.randint(0, self.word_idx_size)
                         for _ in range(self.window_size))
        n = rng.randint(4, 32)
        seq = rng.randint(0, self.word_idx_size, size=(n,)).astype("int64")
        return seq[:-1], seq[1:]


class Movielens(_SyntheticCorpus):
    """(user features, movie features, rating) tuples (reference
    ``text/datasets/movielens.py``)."""

    max_user_id = 6040
    max_movie_id = 3952

    def _make(self, rng):
        user_id = np.asarray(rng.randint(1, self.max_user_id), "int64")
        gender = np.asarray(rng.randint(0, 2), "int64")
        age = np.asarray(rng.randint(0, 7), "int64")
        job = np.asarray(rng.randint(0, 21), "int64")
        movie_id = np.asarray(rng.randint(1, self.max_movie_id), "int64")
        categories = rng.randint(0, 19, size=(rng.randint(1, 4),)).astype(
            "int64")
        title = rng.randint(0, 5175, size=(rng.randint(1, 10),)).astype(
            "int64")
        rating = np.asarray([float(rng.randint(1, 6))], "float32")
        return (user_id, gender, age, job, movie_id, categories, title,
                rating)


class Conll05st(_SyntheticCorpus):
    """SRL fields: word/predicate/ctx windows/mark -> label seq (reference
    ``text/datasets/conll05.py``)."""

    word_dict_size = 44068
    label_dict_size = 106
    predicate_dict_size = 3162

    def _make(self, rng):
        n = rng.randint(8, 64)
        words = rng.randint(0, self.word_dict_size, size=(n,)).astype("int64")
        predicate = np.full((n,), rng.randint(0, self.predicate_dict_size),
                            "int64")
        ctx = [rng.randint(0, self.word_dict_size, size=(n,)).astype("int64")
               for _ in range(4)]
        mark = (rng.rand(n) < 0.2).astype("int64")
        label = rng.randint(0, self.label_dict_size, size=(n,)).astype(
            "int64")
        return (words, predicate, *ctx, mark, label)

    def get_dict(self):
        return ({f"w{i}": i for i in range(self.word_dict_size)},
                {f"p{i}": i for i in range(self.predicate_dict_size)},
                {f"l{i}": i for i in range(self.label_dict_size)})


class _WMT(_SyntheticCorpus):
    """src ids, trg ids (shifted), trg ids (next) triples."""

    def __init__(self, mode="train", src_dict_size=30000, trg_dict_size=30000,
                 lang="en", data_file=None):
        self.src_dict_size = src_dict_size
        self.trg_dict_size = trg_dict_size
        self.lang = lang
        super().__init__(mode=mode, data_file=data_file)

    def _make(self, rng):
        n_src = rng.randint(4, 50)
        n_trg = rng.randint(4, 50)
        src = rng.randint(0, self.src_dict_size, size=(n_src,)).astype(
            "int64")
        trg = rng.randint(0, self.trg_dict_size, size=(n_trg,)).astype(
            "int64")
        trg_in = np.concatenate([[0], trg[:-1]]).astype("int64")  # <s> shift
        return src, trg_in, trg

    def _vocab(self, size, reverse):
        d = {f"tok{i}": i for i in range(size)}
        return {v: k for k, v in d.items()} if reverse else d


class WMT14(_WMT):
    """WMT14 en-fr (reference ``text/datasets/wmt14.py`` — single
    ``dict_size`` shared by both sides)."""

    def __init__(self, mode="train", dict_size=30000, data_file=None):
        super().__init__(mode=mode, src_dict_size=dict_size,
                         trg_dict_size=dict_size, data_file=data_file)

    def get_dict(self, reverse=False):
        return self._vocab(self.src_dict_size, reverse)


class WMT16(_WMT):
    """WMT16 en-de (reference ``text/datasets/wmt16.py`` — per-language
    ``get_dict(lang, reverse)``)."""

    def get_dict(self, lang="en", reverse=False):
        size = self.src_dict_size if lang == self.lang else \
            self.trg_dict_size
        return self._vocab(size, reverse)

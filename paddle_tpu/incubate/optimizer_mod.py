"""Incubate optimizers (reference python/paddle/incubate/optimizer/)."""
from __future__ import annotations

import jax.numpy as jnp

from ..optimizer.optimizers import Optimizer
from ..core import autograd


class LookAhead(Optimizer):
    """lookahead wrapper: slow weights sync every k steps."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        self.inner = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        self._slow = {}
        self._parameter_list = inner_optimizer._parameter_list

    def get_lr(self):
        return self.inner.get_lr()

    @autograd.no_grad()
    def step(self):
        self.inner.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in self._parameter_list or []:
                key = id(p)
                slow = self._slow.get(key, p._data)
                slow = slow + self.alpha * (p._data - slow)
                self._slow[key] = slow
                p._data = slow

    def clear_grad(self, set_to_zero=False):
        self.inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, None


class ModelAverage(Optimizer):
    """Maintains an EMA/average of parameters for eval (reference
    incubate/optimizer/modelaverage.py)."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self._parameter_list = list(parameters) if parameters else None
        self._sums = {}
        self._counts = {}

    @autograd.no_grad()
    def step(self):
        for p in self._parameter_list or []:
            key = id(p)
            self._sums[key] = self._sums.get(key, 0.0) + p._data
            self._counts[key] = self._counts.get(key, 0) + 1

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            saved = {}
            for p in self._parameter_list or []:
                key = id(p)
                if key in self._sums:
                    saved[key] = p._data
                    p._data = self._sums[key] / self._counts[key]
            try:
                yield
            finally:
                if need_restore:
                    for p in self._parameter_list or []:
                        if id(p) in saved:
                            p._data = saved[id(p)]
        return guard()

    def restore(self, executor=None):
        pass

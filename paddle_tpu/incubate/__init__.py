"""paddle.incubate (LookAhead/ModelAverage + experimental nn)."""
from . import optimizer_mod as optimizer  # noqa: F401
from . import nn  # noqa: F401

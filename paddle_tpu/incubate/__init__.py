"""paddle.incubate (LookAhead/ModelAverage + experimental nn)."""
from . import optimizer_mod as optimizer  # noqa: F401
from . import nn  # noqa: F401
from .ops_mod import (softmax_mask_fuse,  # noqa: F401
                      softmax_mask_fuse_upper_triangle, segment_sum,
                      segment_mean, segment_min, segment_max)
from .optimizer_mod import LookAhead, ModelAverage  # noqa: F401
# CTR-stack contrib layers (reference fluid/contrib/layers/nn.py:785
# shuffle_batch, :1498 batch_fc, tdm_child/tdm_sampler, filter_by_instag;
# fluid/layers hash; operators/lookup_table_dequant_op.h)
from ..ops.ctr import (shuffle_batch, batch_fc,  # noqa: F401
                       hash_op, tdm_child, lookup_table_dequant,
                       filter_by_instag, tdm_sampler, rank_attention)

"""incubate operators + segment tensor math.

Reference parity: ``incubate/operators/softmax_mask_fuse*.py`` (fused
CUDA kernels at ``operators/fused/fused_softmax_mask*.cu``) and
``incubate/tensor/math.py:22-179`` segment_{sum,mean,min,max}
(``operators/segment_pool_op``).

TPU-first: the mask+softmax "fusion" is a single traced expression XLA
fuses on its own; segment reductions lower to ``jax.ops.segment_*``
(one-hot matmul or scatter on TPU, picked by XLA).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.dispatch import dispatch
from ..core.tensor import to_tensor

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
           "segment_sum", "segment_mean", "segment_min", "segment_max"]


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) over the last axis (reference
    ``incubate/operators/softmax_mask_fuse.py``)."""
    x, mask = to_tensor(x), to_tensor(mask)
    return dispatch("softmax_mask_fuse",
                    lambda a, m: jax.nn.softmax(a + m, axis=-1),
                    (x, mask), {})


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax (reference
    ``softmax_mask_fuse_upper_triangle.py``): positions above the
    diagonal are masked out."""
    x = to_tensor(x)

    def impl(a):
        T1, T2 = a.shape[-2], a.shape[-1]
        mask = jnp.tril(jnp.ones((T1, T2), bool), k=T2 - T1)
        return jax.nn.softmax(jnp.where(mask, a, -1e9), axis=-1)
    return dispatch("softmax_mask_fuse_upper_triangle", impl, (x,), {})


def _num_segments(data, segment_ids):
    """Tight size eagerly (one host sync per eager call — the id maximum
    decides the output shape); under jit the output size must be static,
    so pad to the upper bound (rows past max(ids) hold the identity)."""
    if isinstance(segment_ids._data, jax.core.Tracer):
        return int(data._data.shape[0])
    if segment_ids._data.size == 0:
        return 0
    return int(jax.device_get(
        jnp.max(segment_ids._data.astype(jnp.int32)))) + 1


def _segment(op_name, reducer):
    def op(data, segment_ids, name=None):
        data, segment_ids = to_tensor(data), to_tensor(segment_ids)
        n = _num_segments(data, segment_ids)

        def impl(d, ids):
            return reducer(d, ids.astype(jnp.int32), num_segments=n)
        return dispatch(op_name, impl, (data, segment_ids), {})
    op.__name__ = op_name
    return op


segment_sum = _segment("segment_sum", jax.ops.segment_sum)
segment_max = _segment("segment_max", jax.ops.segment_max)
segment_min = _segment("segment_min", jax.ops.segment_min)


def segment_mean(data, segment_ids, name=None):
    """Per-segment mean (reference ``incubate/tensor/math.py:74``)."""
    data, segment_ids = to_tensor(data), to_tensor(segment_ids)
    n = _num_segments(data, segment_ids)

    def impl(d, i):
        i = i.astype(jnp.int32)
        total = jax.ops.segment_sum(d, i, num_segments=n)
        counts = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), i,
                                     num_segments=n)
        shape = (n,) + (1,) * (d.ndim - 1)
        return total / jnp.maximum(counts, 1).reshape(shape)
    return dispatch("segment_mean", impl, (data, segment_ids), {})

"""Fused transformer functional ops
(reference python/paddle/incubate/nn/functional/fused_transformer.py).

TPU-first: the reference fuses attention + dropout + residual + LN in
hand-written CUDA (``operators/fused/fused_attention_op.cu``,
``fused_feedforward_op.cu``).  Here the attention core is the pallas
flash-attention kernel (on TPU) and everything around it is expressed as
one traced function — XLA fuses the bias/dropout/residual/LN epilogue
into neighboring kernels, which is exactly what the CUDA fusion
hand-codes.
"""
from __future__ import annotations

from typing import Optional

from .... import ops as P
from ....core.tensor import Tensor, to_tensor
from ....ops.fused_ops import \
    fused_bias_dropout_residual_layer_norm  # noqa: F401

__all__ = ["fused_multi_head_attention", "fused_feedforward",
           "fused_bias_dropout_residual_layer_norm"]


def _maybe_ln(x, scale, bias, eps):
    D = int(x.shape[-1])
    return P.layer_norm(x, [D],
                        None if scale is None else to_tensor(scale),
                        None if bias is None else to_tensor(bias), eps)


def fused_multi_head_attention(x, qkv_weight, linear_weight,
                               pre_layer_norm=False, pre_ln_scale=None,
                               pre_ln_bias=None, ln_scale=None, ln_bias=None,
                               pre_ln_epsilon=1e-5, qkv_bias=None,
                               linear_bias=None, attn_mask=None,
                               dropout_rate=0.5, attn_dropout_rate=0.5,
                               ln_epsilon=1e-5, training=True, name=None):
    """Self-attention block (reference ``fused_transformer.py:176``):

    ``out = LN(x + dropout(linear(MHA(maybe_LN(x)))))`` (post-LN) or the
    pre-LN variant.  ``qkv_weight`` is the reference layout
    ``[3, num_heads, head_dim, embed_dim]``; ``qkv_bias``
    ``[3, num_heads, head_dim]``.
    """
    x = to_tensor(x)
    qkv_weight = to_tensor(qkv_weight)
    linear_weight = to_tensor(linear_weight)
    _, H, Dh, D = (int(s) for s in qkv_weight.shape)

    residual = x
    h = _maybe_ln(x, pre_ln_scale, pre_ln_bias, pre_ln_epsilon) \
        if pre_layer_norm else x

    # qkv projection: [B,T,D] x [3,H,Dh,D] -> [B,T,3,H,Dh]
    w = P.reshape(P.transpose(qkv_weight, [3, 0, 1, 2]), [D, 3 * H * Dh])
    qkv = P.matmul(h, w)
    if qkv_bias is not None:
        qkv = qkv + P.reshape(to_tensor(qkv_bias), [3 * H * Dh])
    B, T = int(x.shape[0]), int(x.shape[1])
    qkv = P.reshape(qkv, [B, T, 3, H, Dh])
    q = P.squeeze(P.slice(qkv, [2], [0], [1]), axis=2)   # [B,T,H,Dh]
    k = P.squeeze(P.slice(qkv, [2], [1], [2]), axis=2)
    v = P.squeeze(P.slice(qkv, [2], [2], [3]), axis=2)

    ctx = P.scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask,
        dropout_p=attn_dropout_rate if training else 0.0,
        training=training)
    ctx = P.reshape(ctx, [B, T, H * Dh])

    out = P.matmul(ctx, linear_weight)
    if not pre_layer_norm:
        # post-LN epilogue rides the fused pallas kernel (one HBM pass,
        # reference fused_dropout_helper.h)
        return fused_bias_dropout_residual_layer_norm(
            out, residual, bias=linear_bias, ln_scale=ln_scale,
            ln_bias=ln_bias, dropout_rate=dropout_rate,
            ln_epsilon=ln_epsilon, training=training)
    if linear_bias is not None:
        out = out + to_tensor(linear_bias)
    out = P.dropout(out, p=dropout_rate, training=training)
    return residual + out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      name=None):
    """FFN block (reference ``fused_transformer.py:31``):
    ``out = LN(x + dropout2(linear2(dropout1(act(linear1(maybe_LN(x)))))))``.
    """
    x = to_tensor(x)
    residual = x
    h = _maybe_ln(x, ln1_scale, ln1_bias, ln1_epsilon) \
        if pre_layer_norm else x
    h = P.matmul(h, to_tensor(linear1_weight))
    if linear1_bias is not None:
        h = h + to_tensor(linear1_bias)
    act = getattr(P, activation)
    h = act(h)
    h = P.dropout(h, p=dropout1_rate, training=training)
    h = P.matmul(h, to_tensor(linear2_weight))
    if not pre_layer_norm:
        return fused_bias_dropout_residual_layer_norm(
            h, residual, bias=linear2_bias, ln_scale=ln2_scale,
            ln_bias=ln2_bias, dropout_rate=dropout2_rate,
            ln_epsilon=ln2_epsilon, training=training)
    if linear2_bias is not None:
        h = h + to_tensor(linear2_bias)
    h = P.dropout(h, p=dropout2_rate, training=training)
    return residual + h

"""Fused transformer layers
(reference python/paddle/incubate/nn/layer/fused_transformer.py:25,234).

The layer surface matches the reference (qkv packed weight layout
``[3, H, Dh, D]``); the compute goes through
``incubate.nn.functional.fused_*`` which rides the pallas flash-attention
kernel on TPU.
"""
from __future__ import annotations

from ....nn.layer_base import Layer
from ....nn import initializer as I
from .. import functional as F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(Layer):
    """Fused self-attention block (reference ``fused_transformer.py:25``)."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        if embed_dim <= 0 or num_heads <= 0:
            raise ValueError("embed_dim and num_heads must be positive")
        if embed_dim % num_heads != 0:
            raise ValueError("embed_dim must be divisible by num_heads")
        if need_weights:
            raise ValueError("need_weights=True is not supported by the "
                             "fused kernel (reference parity)")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                                  else attn_dropout_rate)
        self.normalize_before = normalize_before

        H, Dh, D = num_heads, self.head_dim, embed_dim
        self.qkv_weight = self.create_parameter(
            [3, H, Dh, D], attr=weight_attr)
        self.qkv_bias = self.create_parameter(
            [3, H, Dh], attr=bias_attr, is_bias=True)
        self.linear_weight = self.create_parameter([D, D], attr=weight_attr)
        self.linear_bias = self.create_parameter([D], attr=bias_attr,
                                                 is_bias=True)
        self.pre_ln_scale = self.create_parameter(
            [D], default_initializer=I.Constant(1.0))
        self.pre_ln_bias = self.create_parameter([D], is_bias=True)
        self.ln_scale = self.create_parameter(
            [D], default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([D], is_bias=True)

    def forward(self, query, key=None, value=None, attn_mask=None,
                cache=None):
        if cache is not None:
            raise NotImplementedError("incremental cache not supported")
        if (key is not None and key is not query) or \
                (value is not None and value is not query):
            raise NotImplementedError(
                "the fused kernel only supports self-attention (reference "
                "fused_attention_op parity); pass query alone")
        return F.fused_multi_head_attention(
            query, self.qkv_weight, self.linear_weight,
            pre_layer_norm=self.normalize_before,
            pre_ln_scale=self.pre_ln_scale, pre_ln_bias=self.pre_ln_bias,
            ln_scale=self.ln_scale, ln_bias=self.ln_bias,
            qkv_bias=self.qkv_bias, linear_bias=self.linear_bias,
            attn_mask=attn_mask, dropout_rate=self.dropout_rate,
            attn_dropout_rate=self.attn_dropout_rate,
            training=self.training)


class FusedFeedForward(Layer):
    """Fused FFN block (reference ``fused_transformer.py:234``)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 activation="relu", act_dropout_rate=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.d_model = d_model
        self.dim_feedforward = dim_feedforward
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.normalize_before = normalize_before

        self.linear1_weight = self.create_parameter(
            [d_model, dim_feedforward], attr=weight_attr)
        self.linear1_bias = self.create_parameter(
            [dim_feedforward], attr=bias_attr, is_bias=True)
        self.linear2_weight = self.create_parameter(
            [dim_feedforward, d_model], attr=weight_attr)
        self.linear2_bias = self.create_parameter([d_model], attr=bias_attr,
                                                  is_bias=True)
        self.ln1_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln1_bias = self.create_parameter([d_model], is_bias=True)
        self.ln2_scale = self.create_parameter(
            [d_model], default_initializer=I.Constant(1.0))
        self.ln2_bias = self.create_parameter([d_model], is_bias=True)

    def forward(self, src, cache=None):
        return F.fused_feedforward(
            src, self.linear1_weight, self.linear2_weight,
            linear1_bias=self.linear1_bias, linear2_bias=self.linear2_bias,
            ln1_scale=self.ln1_scale, ln1_bias=self.ln1_bias,
            ln2_scale=self.ln2_scale, ln2_bias=self.ln2_bias,
            dropout1_rate=self.act_dropout_rate,
            dropout2_rate=self.dropout_rate,
            activation=self.activation,
            pre_layer_norm=self.normalize_before,
            training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """Encoder layer = fused attention + fused FFN (reference
    ``fused_transformer.py`` FusedTransformerEncoderLayer)."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before, weight_attr=weight_attr,
            bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation,
            act_dropout_rate=(dropout_rate if act_dropout_rate is None
                              else act_dropout_rate),
            normalize_before=normalize_before, weight_attr=weight_attr,
            bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))


class FusedBiasDropoutResidualLayerNorm(Layer):
    """``LayerNorm(residual + dropout(x + bias))`` as a layer.

    Reference: ``incubate.nn.FusedBiasDropoutResidualLayerNorm`` backed by
    ``operators/fused/fused_dropout_helper.h``; here one pallas kernel
    (ops/pallas/fused_ln.py).
    """

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter(
            [embed_dim], attr=bias_attr, is_bias=True)
        self.ln_scale = self.create_parameter(
            [embed_dim], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.ln_bias = self.create_parameter([embed_dim], is_bias=True)

    def forward(self, x, residual):
        return F.fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)

    def extra_repr(self):
        return f"embed_dim={self.embed_dim}, p={self.dropout_rate}"


__all__.append("FusedBiasDropoutResidualLayerNorm")

"""paddle.incubate.nn (reference python/paddle/incubate/nn/__init__.py)."""
from . import functional  # noqa: F401
from .layer.fused_transformer import (FusedMultiHeadAttention,  # noqa: F401
                                      FusedFeedForward,
                                      FusedTransformerEncoderLayer,
                                      FusedBiasDropoutResidualLayerNorm)

__all__ = ["functional", "FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer",
           "FusedBiasDropoutResidualLayerNorm"]

def vjp(*a, **k):
    raise NotImplementedError("stub")
jvp = jacobian = hessian = vjp

"""Functional autodiff extras (reference python/paddle/autograd/functional.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .core.tensor import Tensor, to_tensor

__all__ = ["vjp", "jvp", "jacobian", "hessian", "vhp"]


def _fn_on_arrays(func):
    def f(*arrays):
        tensors = [Tensor(a) for a in arrays]
        out = func(*tensors)
        if isinstance(out, (tuple, list)):
            return tuple(o._data for o in out)
        return out._data
    return f


def vjp(func, xs, v=None):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [to_tensor(x)._data for x in xs_l]
    out, vjp_fn = jax.vjp(_fn_on_arrays(func), *arrays)
    if v is None:
        v_arr = jnp.ones_like(out) if not isinstance(out, tuple) else \
            tuple(jnp.ones_like(o) for o in out)
    else:
        v_l = v if isinstance(v, (list, tuple)) else [v]
        v_arr = tuple(to_tensor(t)._data for t in v_l)
        if not isinstance(out, tuple):
            v_arr = v_arr[0]
    grads = vjp_fn(v_arr)
    wrap = lambda o: Tensor(o) if not isinstance(o, tuple) else \
        tuple(Tensor(x) for x in o)
    return wrap(out), [Tensor(g) for g in grads]


def jvp(func, xs, v=None):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [to_tensor(x)._data for x in xs_l]
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        v_l = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(to_tensor(t)._data for t in v_l)
    out, tangent_out = jax.jvp(_fn_on_arrays(func), tuple(arrays), tangents)
    wrap = lambda o: Tensor(o) if not isinstance(o, tuple) else \
        tuple(Tensor(x) for x in o)
    return wrap(out), wrap(tangent_out)


def jacobian(func, xs, create_graph=False, allow_unused=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [to_tensor(x)._data for x in xs_l]
    jac = jax.jacrev(_fn_on_arrays(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    if len(arrays) == 1:
        jac = jac[0] if isinstance(jac, tuple) else jac
        return Tensor(jac)
    return tuple(Tensor(j) for j in jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    arrays = [to_tensor(x)._data for x in xs_l]
    h = jax.hessian(_fn_on_arrays(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    if len(arrays) == 1:
        h = h[0][0] if isinstance(h, tuple) else h
        return Tensor(h)
    return jax.tree_util.tree_map(Tensor, h)


def vhp(func, inputs, v=None):
    """vector-Hessian product for a scalar-output func (reference
    autograd/functional.py vhp)."""
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    arrays = [to_tensor(x)._data for x in ins]
    if v is None:
        vs = tuple(jnp.ones_like(a) for a in arrays)
    else:
        v_l = v if isinstance(v, (list, tuple)) else [v]
        vs = tuple(to_tensor(t)._data for t in v_l)
    f = _fn_on_arrays(func)

    def scalar_f(*a):
        out = f(*a)
        return jnp.sum(out)
    out = f(*arrays)
    grad_fn = jax.grad(scalar_f, argnums=tuple(range(len(arrays))))
    _, hvp = jax.jvp(grad_fn, tuple(arrays), vs)
    hvps = hvp if isinstance(hvp, tuple) else (hvp,)
    return Tensor(out), [Tensor(h) for h in hvps]

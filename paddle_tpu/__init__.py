"""paddle_tpu: a TPU-native deep-learning framework.

Brand-new implementation of the capability surface of kerwinner/Paddle
(PaddlePaddle ~v2.2), designed TPU-first on JAX/XLA/pallas/pjit:

- eager (dygraph) runtime with tape autograd over jax.vjp
- jit/static path (``paddle_tpu.jit.to_static`` == traced+compiled XLA)
- nn module system, optimizers, AMP, DataLoader, Model.fit hapi
- distributed: device-mesh topology, named-axis collectives, DP/TP/PP/
  ZeRO-sharding/recompute, sequence-parallel ring attention
- pallas kernels for the fused hot paths (flash attention, fused LN)

The public namespace mirrors ``paddle.*`` so reference users can switch.
"""
from __future__ import annotations

__version__ = "0.1.0"

# core surface
from .core.tensor import Tensor, Parameter, to_tensor  # noqa: F401
from .core.place import (  # noqa: F401
    CPUPlace, TPUPlace, CUDAPinnedPlace, set_device, get_device,
    device_count, is_compiled_with_tpu,
)
from .core.autograd import no_grad, enable_grad, set_grad_enabled, \
    is_grad_enabled, grad  # noqa: F401
from .core.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .core import dtype as _dtype_mod
from .core import errors  # noqa: F401  (enforce taxonomy, enforce.h:422)
from .core.dtype import (  # noqa: F401
    float32, float64, float16, bfloat16, int8, int16, int32, int64, uint8,
    bool_, complex64, complex128,
)

# whole op surface re-exported at top level (paddle.* style)
from .ops import *  # noqa: F401,F403
from . import ops  # noqa: F401

# subsystem namespaces
# NB: `from .ops import *` above binds the ops.linalg submodule onto this
# package under the name `linalg`; rebind to the real paddle_tpu.linalg
# namespace module (which re-exports the op set and adds cond etc.).
import importlib as _importlib

linalg = _importlib.import_module(".linalg", __name__)
cond = linalg.cond  # paddle.cond == paddle.linalg.cond (reference export)
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import metric  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import inference  # noqa: F401
from . import serving  # noqa: F401
from . import vision  # noqa: F401
from . import distributed  # noqa: F401
from . import autograd  # noqa: F401
from . import distribution  # noqa: F401
from . import text  # noqa: F401
from . import hub  # noqa: F401
from . import sparsity  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import callbacks  # noqa: F401
from . import sysconfig  # noqa: F401
from . import onnx  # noqa: F401
from .batch import batch  # noqa: F401
from . import regularizer  # noqa: F401
from . import device  # noqa: F401
from . import incubate  # noqa: F401
from . import profiler as profiler_mod  # noqa: F401
from . import utils  # noqa: F401

from .nn.param_attr import ParamAttr  # noqa: F401
from .framework_io import save, load  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi import summary, flops  # noqa: F401
from .utils.flags import get_flags, set_flags  # noqa: F401

# paddle.disable_static / enable_static compatibility: the dygraph mode is
# the default; enable_static() switches the `static` module's executor into
# program-capture mode.
from .static.mode import enable_static, disable_static, in_dynamic_mode  # noqa: F401


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False

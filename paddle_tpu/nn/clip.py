"""Gradient clipping (reference python/paddle/fluid/clip.py:
ClipGradByValue/Norm/GlobalNorm).  Used by optimizers via grad_clip=...
Works on (param, grad) tensor pairs in eager mode and on grad pytrees in
the jitted path.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (param, grad Tensor) pairs."""
        raise NotImplementedError

    def _clip_arrays(self, grads):
        """Functional form for the jit path: list of arrays -> list."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max))))
        return out

    def _clip_arrays(self, grads):
        return [None if g is None else jnp.clip(g, self.min, self.max)
                for g in grads]


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g)))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return g * scale

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(self._one(g._data))))
        return out

    def _clip_arrays(self, grads):
        return [None if g is None else self._one(g) for g in grads]


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _scale(self, arrays):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in arrays if g is not None]
        if not sq:
            return None
        total = jnp.sqrt(jnp.sum(jnp.stack(sq)))
        return jnp.minimum(self.clip_norm / jnp.maximum(total, 1e-12), 1.0)

    def __call__(self, params_grads):
        from ..core.selected_rows import SelectedRows

        # MergeAdd SelectedRows first (reference merges before
        # clip_by_global_norm): repeated rows must contribute the squared
        # merged row, not sum-of-squares of individual slices, or the
        # norm is underestimated and the grads under-clipped.
        merged_pairs = [
            (p, g.merged() if isinstance(g, SelectedRows) else g)
            for p, g in params_grads]

        def arr(g):
            return g.values if isinstance(g, SelectedRows) else g._data
        clippable = [arr(g) for p, g in merged_pairs
                     if g is not None and getattr(p, "need_clip", True)]
        scale = self._scale(clippable)
        if scale is None:
            return merged_pairs
        out = []
        for p, g in merged_pairs:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            elif isinstance(g, SelectedRows):
                out.append((p, g.scale(scale.astype(g.values.dtype))))
            else:
                out.append((p, Tensor((g._data.astype(jnp.float32) * scale)
                                      .astype(g._data.dtype))))
        return out

    def _clip_arrays(self, grads):
        scale = self._scale([g for g in grads if g is not None])
        if scale is None:
            return grads
        return [None if g is None else
                (g.astype(jnp.float32) * scale).astype(g.dtype)
                for g in grads]

"""paddle.nn.functional namespace — re-exports the functional op surface.

Reference parity: ``python/paddle/nn/functional/`` (activation, common,
conv, loss, norm, pooling, vision, sparse_attention modules).
"""
from __future__ import annotations

import jax

# activations
from ..ops.activation import *  # noqa: F401,F403
# conv / pool / vision
from ..ops.conv import *  # noqa: F401,F403
# norm
from ..ops.norm_ops import *  # noqa: F401,F403
# losses
from ..ops.loss import *  # noqa: F401,F403
# embedding/dropout/linear/attention
from ..ops.nn_misc import *  # noqa: F401,F403
# padding & one-hot style utilities
from ..ops.manipulation import pad  # noqa: F401
from ..ops.loss import one_hot  # noqa: F401
from ..ops.activation import gumbel_softmax  # noqa: F401

# flash attention namespace parity with paddle.nn.functional.flash_attention
from ..ops.nn_misc import scaled_dot_product_attention as flash_attention  # noqa: F401

# in-place activation variants (reference paddle.nn.functional elu_/...)
from ..ops.extras import _inplace_guard as __ipg  # noqa: E402


def elu_(x, alpha=1.0, name=None):
    __ipg(x, "elu_")
    from ..ops.activation import elu
    from ..core.tensor import Tensor as _T
    x._data = elu(_T(x._data), alpha)._data
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    __ipg(x, "softmax_")
    from ..ops.activation import softmax
    from ..core.tensor import Tensor as _T
    x._data = softmax(_T(x._data), axis=axis, dtype=dtype)._data
    return x


def tanh_(x, name=None):
    from ..ops.extras import tanh_ as _tanh_
    return _tanh_(x)


def gather_tree(ids, parents):
    """Backtrace beam-search ids along parent pointers (reference
    gather_tree_op): ids/parents [T, B, beam] -> full sequences."""
    import jax.numpy as _jnp
    from ..core.dispatch import dispatch as _dispatch
    from ..core.tensor import to_tensor as _tt
    ids, parents = _tt(ids), _tt(parents)

    def impl(i, p):
        T = i.shape[0]

        def body(carry, xs):
            beam_idx = carry                       # (B, beam)
            step_ids, step_parents = xs
            out = _jnp.take_along_axis(step_ids, beam_idx, axis=-1)
            prev = _jnp.take_along_axis(step_parents, beam_idx, axis=-1)
            return prev, out

        init = _jnp.broadcast_to(
            _jnp.arange(i.shape[-1], dtype=p.dtype), i.shape[1:])
        _, outs = jax.lax.scan(body, init, (i[::-1], p[::-1]))
        return outs[::-1]
    return _dispatch("gather_tree", impl, (ids, parents), {})

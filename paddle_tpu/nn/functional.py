"""paddle.nn.functional namespace — re-exports the functional op surface.

Reference parity: ``python/paddle/nn/functional/`` (activation, common,
conv, loss, norm, pooling, vision, sparse_attention modules).
"""
from __future__ import annotations

import jax

# activations
from ..ops.activation import *  # noqa: F401,F403
# conv / pool / vision
from ..ops.conv import *  # noqa: F401,F403
# norm
from ..ops.norm_ops import *  # noqa: F401,F403
# losses
from ..ops.loss import *  # noqa: F401,F403
# embedding/dropout/linear/attention
from ..ops.nn_misc import *  # noqa: F401,F403
# padding & one-hot style utilities
from ..ops.manipulation import pad  # noqa: F401
from ..ops.loss import one_hot  # noqa: F401
from ..ops.activation import gumbel_softmax  # noqa: F401

# flash attention namespace parity with paddle.nn.functional.flash_attention
from ..ops.nn_misc import scaled_dot_product_attention as flash_attention  # noqa: F401

# in-place activation variants (reference paddle.nn.functional elu_/...)
from ..ops.extras import _inplace_guard as __ipg  # noqa: E402


def elu_(x, alpha=1.0, name=None):
    __ipg(x, "elu_")
    from ..ops.activation import elu
    from ..core.tensor import Tensor as _T
    x._data = elu(_T(x._data), alpha)._data
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    __ipg(x, "softmax_")
    from ..ops.activation import softmax
    from ..core.tensor import Tensor as _T
    x._data = softmax(_T(x._data), axis=axis, dtype=dtype)._data
    return x


def tanh_(x, name=None):
    from ..ops.extras import tanh_ as _tanh_
    return _tanh_(x)


def fused_conv_bn(x, conv, bn=None, act="relu", pre_norm=False):
    """Run a conv/batch-norm/activation block as ONE fused dispatch.

    ``conv`` is an ``nn.Conv2D``-style layer (1d/3d work too) and ``bn``
    an optional ``nn.BatchNorm*`` layer; ``act`` is ``"relu"``-style or
    ``None``.  ``pre_norm=True`` runs the DenseNet ordering
    (norm → act → conv) instead of conv → norm → act.

    Honors ``FLAGS_fused_conv``: when off — or in static-capture mode
    (the program-level ``fusion_group`` pass owns that fusion), under
    an active AMP autocast (the eager cast lists are per-op), or for
    configs the fused kernels don't cover — it falls back to the exact
    eager composition, which is also the bit-parity reference the
    tests pin the fused path against.
    """
    from ..core.tensor import to_tensor as _tt
    from ..ops import fused_conv as _fc
    from ..ops import activation as _act_ops
    from ..utils import flags as _flags
    from ..amp import _amp_state

    def _eager():
        if pre_norm:
            out = x
            if bn is not None:
                out = bn(_tt(out))
            if act:
                out = getattr(_act_ops, act)(out)
            return conv(out)
        out = conv(_tt(x))
        if bn is not None:
            out = bn(out)
        if act:
            out = getattr(_act_ops, act)(out)
        return out

    from .layer.conv import _ConvNd
    fusable = (
        _flags.get_flag("FLAGS_fused_conv")
        and isinstance(conv, _ConvNd)   # quantized/custom convs: eager
        and act in _fc._ACTS
        and not getattr(conv, "_transposed", False)
        and getattr(conv, "_padding_mode", "zeros") == "zeros"
        # registered hooks are an observable contract (PTQ calibration
        # records conv inputs via pre-hooks) — they only fire through
        # Layer.__call__, so hooked convs take the eager composition
        and not conv._forward_pre_hooks
        and not conv._forward_post_hooks
        and _amp_state() is None)
    if not fusable:
        return _eager()
    if bn is not None:
        from .layer.norm import (BatchNorm1D, BatchNorm2D, BatchNorm3D,
                                 _BatchNormBase)
        # exact types only: subclasses (SyncBatchNorm, the legacy act-
        # carrying BatchNorm) override forward with semantics the fused
        # kernel must not silently replace.  BatchNorm1D's forward is
        # the generic batch_norm modulo the NCL/NLC format alias, which
        # the nd-generic fused kernels derive from the conv layout.
        if type(bn) not in (BatchNorm1D, BatchNorm2D, BatchNorm3D,
                            _BatchNormBase) or \
                bn.weight is None or bn.bias is None or \
                bn._use_global_stats or \
                bn._forward_pre_hooks or bn._forward_post_hooks:
            return _eager()
    from ..static.mode import in_dynamic_mode
    if not in_dynamic_mode():
        # static capture: emit the plain conv/batch_norm/act ops — the
        # extended fusion_group pass fuses them at the program level
        # (and conv_bn_fold folds the eval form when enabled)
        return _eager()

    x = _tt(x)
    if not all(isinstance(s, int) for s in
               tuple(x._data.shape) + tuple(conv.weight._data.shape)):
        # symbolic dims (jax.export dynamic-batch tracing): the fused
        # factories key on concrete shapes — the eager composition
        # exports cleanly with symbolic batch
        return _eager()
    kw = dict(stride=conv._stride, padding=conv._padding,
              dilation=conv._dilation, groups=conv._groups,
              data_format=conv._data_format)
    if bn is None:
        if pre_norm:
            return _eager()
        return _fc.fused_conv_act(x, conv.weight, conv.bias, act=act, **kw)
    training = bn.training
    if pre_norm:
        out = _fc.fused_bn_act_conv(
            x, conv.weight, bn.weight, bn.bias, bn._mean, bn._variance,
            bias=conv.bias, epsilon=bn._epsilon, act=act,
            training=training, **kw)
        if not training:
            return out
        y, mu, var = out
    elif training:
        y, mu, var = _fc.fused_conv_bn_act(
            x, conv.weight, bn.weight, bn.bias, bias=conv.bias,
            epsilon=bn._epsilon, act=act, **kw)
    else:
        return _fc.fused_conv_bn_act_infer(
            x, conv.weight, bn.weight, bn.bias, bn._mean, bn._variance,
            bias=conv.bias, epsilon=bn._epsilon, act=act, **kw)
    # running-stat update: same in-place rebind as ops.norm_ops.batch_norm
    # (capture-safe under the jitted train step — buffers are read out
    # after tracing)
    mom = bn._momentum
    bn._mean._data = mom * bn._mean._data + (1.0 - mom) * mu._data
    bn._variance._data = mom * bn._variance._data + (1.0 - mom) * var._data
    return y


def gather_tree(ids, parents):
    """Backtrace beam-search ids along parent pointers (reference
    gather_tree_op): ids/parents [T, B, beam] -> full sequences."""
    import jax.numpy as _jnp
    from ..core.dispatch import dispatch as _dispatch
    from ..core.tensor import to_tensor as _tt
    ids, parents = _tt(ids), _tt(parents)

    def impl(i, p):
        T = i.shape[0]

        def body(carry, xs):
            beam_idx = carry                       # (B, beam)
            step_ids, step_parents = xs
            out = _jnp.take_along_axis(step_ids, beam_idx, axis=-1)
            prev = _jnp.take_along_axis(step_parents, beam_idx, axis=-1)
            return prev, out

        init = _jnp.broadcast_to(
            _jnp.arange(i.shape[-1], dtype=p.dtype), i.shape[1:])
        _, outs = jax.lax.scan(body, init, (i[::-1], p[::-1]))
        return outs[::-1]
    return _dispatch("gather_tree", impl, (ids, parents), {})

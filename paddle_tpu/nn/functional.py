"""paddle.nn.functional namespace — re-exports the functional op surface.

Reference parity: ``python/paddle/nn/functional/`` (activation, common,
conv, loss, norm, pooling, vision, sparse_attention modules).
"""
from __future__ import annotations

# activations
from ..ops.activation import *  # noqa: F401,F403
# conv / pool / vision
from ..ops.conv import *  # noqa: F401,F403
# norm
from ..ops.norm_ops import *  # noqa: F401,F403
# losses
from ..ops.loss import *  # noqa: F401,F403
# embedding/dropout/linear/attention
from ..ops.nn_misc import *  # noqa: F401,F403
# padding & one-hot style utilities
from ..ops.manipulation import pad  # noqa: F401
from ..ops.loss import one_hot  # noqa: F401
from ..ops.activation import gumbel_softmax  # noqa: F401

# flash attention namespace parity with paddle.nn.functional.flash_attention
from ..ops.nn_misc import scaled_dot_product_attention as flash_attention  # noqa: F401

"""nn.Layer: the module system.

Reference parity: ``python/paddle/fluid/dygraph/layers.py:81`` (Layer with
hooks, state_dict, train/eval, parameter registration via __setattr__).
TPU-first addition: ``functional_state`` / ``load_functional_state`` — a
pytree view of (params, buffers) that the jitted train-step path threads
through XLA, so the same Layer object serves both eager and compiled
execution.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import dtype_to_jnp, canonical_dtype
from ..core.tensor import Parameter, Tensor, to_tensor

__all__ = ["Layer"]


class HookRemoveHelper:
    def __init__(self, hooks, key):
        self._hooks = hooks
        self._key = key

    def remove(self):
        self._hooks.pop(self._key, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self._dtype = canonical_dtype(dtype)
        self.training = True
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_counter = 0
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ------------------------------------------------------------------
    # registration
    # ------------------------------------------------------------------
    # bumped on every registration change anywhere; functional-state ref
    # caches key on it (coarse, but structure changes are rare and the
    # walk they replace runs every training step)
    _struct_version = 0

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__() first")
            params[name] = value
            subs.pop(name, None) if subs else None
            if bufs:
                bufs.pop(name, None)
            Layer._struct_version += 1
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            subs[name] = value
            if params:
                params.pop(name, None)
            Layer._struct_version += 1
            object.__setattr__(self, name, value)
        else:
            if params and name in params and value is None:
                params.pop(name)
                Layer._struct_version += 1
            if bufs is not None and name in bufs:
                if isinstance(value, Tensor):
                    bufs[name] = value
                else:
                    bufs.pop(name)
                Layer._struct_version += 1
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        Layer._struct_version += 1
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
            object.__setattr__(self, name, parameter)
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        Layer._struct_version += 1
        self._sub_layers[name] = sublayer
        object.__setattr__(self, name, sublayer)
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        Layer._struct_version += 1
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = to_tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        if tensor is not None:
            object.__setattr__(self, name, tensor)

    def create_parameter(self, shape, attr=None, dtype=None,
                         is_bias: bool = False, default_initializer=None):
        from . import initializer as I
        dtype = dtype or self._dtype
        init = None
        if default_initializer is not None:
            init = default_initializer
        elif attr is not None and getattr(attr, "initializer", None) is not None:
            init = attr.initializer
        else:
            init = I.Constant(0.0) if is_bias else I.XavierNormal()
        data = init(shape, dtype)
        p = Parameter(data, trainable=True)
        if attr is not None and getattr(attr, "name", None):
            p.name = attr.name
        if attr is not None and getattr(attr, "learning_rate", None) is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
        if attr is not None and getattr(attr, "trainable", True) is False:
            p.stop_gradient = True
            p.trainable = False
        if attr is not None:
            p.regularizer = getattr(attr, "regularizer", None)
        return p

    # ------------------------------------------------------------------
    # traversal
    # ------------------------------------------------------------------
    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                sub_prefix = prefix + "." + lname if prefix else lname
                for n, p in layer.named_parameters(sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + name if not prefix else prefix + "." + name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                sub_prefix = prefix + "." + lname if prefix else lname
                yield from layer.named_buffers(sub_prefix)

    def buffers(self, include_sublayers: bool = True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            sub_prefix = prefix + "." + name if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        yield from self._sub_layers.values()

    def named_children(self):
        yield from self._sub_layers.items()

    def apply(self, fn: Callable[["Layer"], None]):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------------
    # modes
    # ------------------------------------------------------------------
    def train(self):
        self.training = True
        for layer in self.children():
            layer.train()
        return self

    def eval(self):
        self.training = False
        for layer in self.children():
            layer.eval()
        return self

    # ------------------------------------------------------------------
    # hooks
    # ------------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        key = self._hook_counter
        self._hook_counter += 1
        self._forward_pre_hooks[key] = hook
        return HookRemoveHelper(self._forward_pre_hooks, key)

    def register_forward_post_hook(self, hook):
        key = self._hook_counter
        self._hook_counter += 1
        self._forward_post_hooks[key] = hook
        return HookRemoveHelper(self._forward_post_hooks, key)

    # ------------------------------------------------------------------
    # call
    # ------------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # ------------------------------------------------------------------
    # state dict
    # ------------------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   use_hook: bool = True) -> Dict[str, Tensor]:
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[name] = p
        for name, b in self.named_buffers():
            leaf = name.rsplit(".", 1)[-1]
            owner = self._locate(name)
            if owner is not None and leaf in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _locate(self, dotted: str) -> Optional["Layer"]:
        parts = dotted.split(".")[:-1]
        layer = self
        for p in parts:
            layer = layer._sub_layers.get(p)
            if layer is None:
                return None
        return layer

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            arr = value._data if isinstance(value, Tensor) else jnp.asarray(value)
            if tuple(arr.shape) != tuple(target._data.shape):
                raise ValueError(
                    f"shape mismatch for '{name}': checkpoint "
                    f"{tuple(arr.shape)} vs model {tuple(target._data.shape)}")
            target._data = arr.astype(target._data.dtype)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ------------------------------------------------------------------
    # dtype/device movement
    # ------------------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        def _move(t):
            if t is None:
                return t
            out = t
            if device is not None:
                out = out.to(device)
            if dtype is not None and jnp.issubdtype(out.dtype, jnp.floating):
                out = out.astype(dtype)
            t._data = out._data
            return t
        for p in self.parameters():
            _move(p)
        for b in self.buffers():
            _move(b)
        if dtype is not None:
            for _, l in self.named_sublayers(include_self=True):
                l._dtype = canonical_dtype(dtype)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # ------------------------------------------------------------------
    # functional state bridge (jit path)
    # ------------------------------------------------------------------
    def _functional_refs(self):
        """Cached (name, tensor) lists for the jit-path state bridge:
        the recursive walk costs ~10 ms/step on a ResNet50-sized tree,
        paid every training step without this.

        INVARIANT: any mutation of a layer's ``_parameters`` /
        ``_sub_layers`` / ``_buffers`` dicts must bump
        ``Layer._struct_version`` (the registration paths above and
        container/quantization swaps all do).  As a safety net against
        direct dict mutation that skips the bump, the cache also keys on
        the walked entry counts — a add/remove that dodged the version
        bump still invalidates; only a same-count swap could serve stale
        refs."""
        def raw_count(l=self):
            # raw registration-dict sizes (not the deduped/None-skipping
            # named_* walks), computed identically at build and check
            # time.  Count-only recursion — no prefix-string building —
            # so the per-step cache check stays ~free
            n = len(l._parameters) + len(l._buffers)
            for c in l._sub_layers.values():
                n += raw_count(c)
            return n

        cache = self.__dict__.get("_fn_ref_cache")
        cv = Layer._struct_version
        if cache is not None and cache[0] == cv and cache[3] == raw_count():
            return cache[1], cache[2]
        prefs = dict(self.named_parameters())
        brefs = dict(self.named_buffers())
        object.__setattr__(self, "_fn_ref_cache",
                           (cv, prefs, brefs, raw_count()))
        return prefs, brefs

    def functional_state(self):
        """Return ({name: jax.Array params}, {name: jax.Array buffers})."""
        prefs, brefs = self._functional_refs()
        params = {n: p._data for n, p in prefs.items()}
        buffers = {n: b._data for n, b in brefs.items()}
        return params, buffers

    def load_functional_state(self, params=None, buffers=None):
        """Rebind arrays (traced or concrete) into the live tensors."""
        prefs, brefs = self._functional_refs()
        if params:
            for n, a in params.items():
                prefs[n]._data = a
        if buffers:
            for n, a in buffers.items():
                brefs[n]._data = a

    def full_name(self):
        return self._name_scope

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    def __repr__(self):
        lines = [self.__class__.__name__ + "("]
        for name, layer in self._sub_layers.items():
            rep = repr(layer).replace("\n", "\n  ")
            lines.append(f"  ({name}): {rep}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else \
            self.__class__.__name__ + "()"

"""paddle.nn namespace (reference python/paddle/nn/__init__.py)."""
from __future__ import annotations

from .layer_base import Layer  # noqa: F401
from .param_attr import ParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401

from .layer.common import *  # noqa: F401,F403
from .layer.conv import *  # noqa: F401,F403
from .layer.norm import *  # noqa: F401,F403
from .layer.pooling import *  # noqa: F401,F403
from .layer.activation import *  # noqa: F401,F403
from .layer.container import *  # noqa: F401,F403
from .layer.loss import *  # noqa: F401,F403
from .layer.rnn import *  # noqa: F401,F403
from .layer.transformer import *  # noqa: F401,F403
from .layer.fused_conv import FusedConvBNReLU  # noqa: F401

from . import clip  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401

from ..core.tensor import Parameter  # noqa: F401

from . import utils_mod as utils  # noqa: F401  (paddle.nn.utils)
from .utils_mod import spectral_norm, weight_norm, remove_weight_norm  # noqa: F401
from .layer import loss  # noqa: F401  (paddle.nn.loss submodule parity)

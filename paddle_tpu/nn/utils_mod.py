"""paddle.nn.utils (reference python/paddle/nn/utils/):
weight_norm / spectral_norm parametrizations + parameters_to_vector.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from .. import ops as P

__all__ = ["spectral_norm", "remove_weight_norm", "weight_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _power_iteration(w2d, u, n_iters, eps=1e-12):
    v = None
    for _ in range(max(1, n_iters)):
        v = w2d.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = w2d @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ (w2d @ v)
    return u, sigma


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide ``layer.<name>`` by its largest singular value before each
    forward (reference ``nn/utils/spectral_norm_hook.py``); the power-
    iteration vector persists as a buffer."""
    weight = getattr(layer, name)
    w = np.asarray(weight._data)
    d = dim if dim is not None else 0
    w2d = np.moveaxis(w, d, 0).reshape(w.shape[d], -1)
    rng = np.random.RandomState(0)
    u0 = rng.randn(w2d.shape[0]).astype(np.float32)
    u0 /= np.linalg.norm(u0) + eps
    layer.register_buffer(f"{name}_u", Tensor(jnp.asarray(u0)))
    layer._spectral_cfg = (name, d, n_power_iterations, eps)

    def hook(lyr, inputs):
        nm, dd, iters, e = lyr._spectral_cfg
        wt = getattr(lyr, nm)
        arr = wt._data
        mat = jnp.moveaxis(arr, dd, 0).reshape(arr.shape[dd], -1)
        u = getattr(lyr, f"{nm}_u")._data
        u, sigma = _power_iteration(mat, u, iters, e)
        getattr(lyr, f"{nm}_u")._data = u
        wt._data = arr / sigma
        return None

    layer.register_forward_pre_hook(hook)
    return layer


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| (reference weight_norm_hook)."""
    weight = getattr(layer, name)
    arr = weight._data
    axes = tuple(i for i in range(arr.ndim) if i != dim)
    g = jnp.sqrt(jnp.sum(arr * arr, axis=axes, keepdims=True))
    layer.register_buffer(f"{name}_g", Tensor(g))
    layer._weight_norm_cfg = (name, dim)

    def hook(lyr, inputs):
        nm, dd = lyr._weight_norm_cfg
        wt = getattr(lyr, nm)
        a = wt._data
        ax = tuple(i for i in range(a.ndim) if i != dd)
        norm = jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=True)) + 1e-12
        wt._data = a / norm * getattr(lyr, f"{nm}_g")._data
        return None

    layer._weight_norm_hook = layer.register_forward_pre_hook(hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Unhook and restore plain-weight behavior (reference
    remove_weight_norm)."""
    handle = getattr(layer, "_weight_norm_hook", None)
    if handle is not None:
        handle.remove()
        del layer._weight_norm_hook
    if hasattr(layer, "_weight_norm_cfg"):
        del layer._weight_norm_cfg
    return layer


def parameters_to_vector(parameters, name=None):
    arrays = [jnp.ravel(p._data) for p in parameters]
    return Tensor(jnp.concatenate(arrays))


def vector_to_parameters(vec, parameters, name=None):
    arr = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape))
        p._data = arr[offset:offset + n].reshape(tuple(p.shape)).astype(
            p._data.dtype)
        offset += n
